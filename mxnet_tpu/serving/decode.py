"""Continuous batching for autoregressive decode — iteration-level
scheduling over a persistent slot pool.

The one-shot engine (engine.py) coalesces requests into a batch,
dispatches ONCE, and scatters results.  Sequence models cannot be
served that way without catastrophic waste: a static batch holds every
finished sequence hostage until the slowest member completes, and new
requests wait for the whole batch to drain.  This module schedules at
the *iteration* level instead (ROADMAP item 1 — THE millions-of-users
workload):

- **one persistent step program** compiled ONCE over a fixed-capacity
  slot pool (``MXNET_DECODE_SLOTS`` slots x ``MXNET_DECODE_MAX_LEN``
  positions).  Requests join and leave the running batch BETWEEN steps
  with zero retraces — shapes never change, so the jit cache is never
  busted (the compile counter is pinned across churn by tests);
- **device-resident per-slot state**: recurrent state (h/c per
  :meth:`~mxnet_tpu.rnn.rnn_cell.BaseRNNCell.begin_state_arrays`) or a
  fixed-layout KV cache in the O(1)-per-token mold of PAPERS.md
  "Compiler-First State Space Duality and Portable O(1) Autoregressive
  Caching" (arxiv 2603.09555): a ``(slots, max_len, d)`` buffer
  written at one position per step, never grown, never re-laid-out.
  State stays in HBM across steps (buffers are donated to the step
  dispatch off-CPU); the host ships only the per-step new-token id
  vector and the slot-occupancy/valid vector, and receives only the
  sampled token ids back;
- **masked dead slots**: free slots ride along in every dispatch
  holding whatever a finished request left behind.  That is sound
  exactly when the step graph is row-local along the slot axis —
  :func:`mxnet_tpu.analysis.check_decode_step` proves it at
  construction with the same padding classifier serving already
  trusts, seeding state inputs pad-DIRTY so stale garbage gets no
  zero-absorption credit (``tools/graph_lint.py --decode-step`` runs
  the same lint offline);
- **bucketed prefill**: a prompt is consumed either token-by-token
  through the running step batch (teacher forcing — no extra
  programs), or, with a ``prefill_sym``, in ONE dispatch through the
  existing :class:`~mxnet_tpu.serving.buckets.ProgramCache` at pow2
  seq buckets, its output state scattered into the free slot —
  and concurrent joiners COALESCE (``MXNET_DECODE_COALESCE_PREFILL``,
  default on): requests joining in the same scheduler iteration whose
  prompts pad to the same seq bucket share one dispatch at the next
  pow2 batch extent instead of prefilling at batch 1 each, the direct
  TTFT lever at concurrency (``perf/decode_bench.py --prefill``);
- **fused-op selection**: before any program compiles, the optimizer's
  kernel-selection pipeline (``analysis.SELECT_OPT_PASSES``, behind
  ``MXNET_SERVE_OPTIMIZE`` + ``MXNET_OPT_SELECT_KERNELS``) rewrites
  the step graph under the same slot-axis/pad-dirty spec the preflight
  lint uses — today swapping the one-hot-blend KV-cache row write
  (O(max_len*d) per token; all XLA's fuser reliably handles, per
  arxiv 2301.13062) for the O(d) ``_cache_write_row`` scatter
  (ops/cache.py: Pallas kernel on TPU, ``dynamic_update_slice``
  elsewhere).  Adoption is verdict-gated exactly like every optimizer
  rewrite: re-analysis no worse, slot row-locality preserved under
  pad-dirty seeding, rejected plans serve the unmodified step.  The
  adopted selection rides the AOT cache's validity fingerprint, so
  toggling it between restarts REJECTS stale entries;
- **per-token streaming**: ``submit(..., on_token=cb)`` fires the
  callback with each generated token id in order (the exact
  ``greedy_decode`` prefix) from the slot loop; a raising callback
  evicts only its own request (SSE per-request streams remain a
  follow-up — this is their engine seam);
- **admission + per-step deadlines**: the same
  :class:`~mxnet_tpu.serving.admission.AdmissionController` front door
  (bounded queue, reject/shed overload policies); deadlines are
  re-checked every iteration, and an expired request — queued or
  mid-generation — completes with its PARTIAL output and the
  ``expired`` flag instead of failing (``Request.on_expire``).

Quick start::

    eng = serving.DecodeEngine(step_sym, params, {}, state_info=[
        {"name": "h", "shape": (H,)}, {"name": "c", "shape": (H,)}])
    eng.warmup()
    fut = eng.submit([bos_id], max_new_tokens=32)
    res = fut.result()          # DecodeResult: tokens, finish_reason
    eng.close()

Step-graph contract: ``step_sym`` outputs ``[logits] + next_states``
(exactly like ``BaseRNNCell.__call__``), over arguments ``token``
(slot vector of last token ids), the state names from ``state_info``
(each ``(slots,) + per_slot_shape``), and optionally ``pos`` (per-slot
write position) and ``valid`` (1/0 occupancy).  The engine appends a
greedy ``argmax`` head so only token ids cross the host boundary.
"""
from __future__ import annotations

import collections
import threading
import time
import warnings
import weakref
from concurrent.futures import Future

import numpy as np

from ..base import MXNetError
from .. import telemetry as _telemetry
from ..telemetry import goodput as _goodput
from . import faults as _faults
from .locks import named_lock, named_condition
from .admission import (AdmissionController, Request, EngineClosedError,
                        _fail_future)
from .buckets import ProgramCache, _next_pow2
from .engine import (_ENGINE_SEQ, _percentile, aot_metric_families,
                     _supervisor_state, memory_metric_families,
                     _memory_stats_block, refresh_memory_gauges)
from .replica import DecodeReplica, resolve_replica_placements

__all__ = ["DecodeEngine", "DecodeResult", "StepProgram", "greedy_decode",
           "Sampler", "GreedySampler", "TemperatureSampler"]


class Sampler(object):
    """Pluggable token-selection head for the decode step (ROADMAP 1a).

    The step program's contract is ``[logits] + next_states``; a
    Sampler decides how the per-slot logits row becomes the sampled
    token id.  ``greedy=True`` samplers keep the original in-graph
    ``argmax`` head — bitwise-pinned against ``greedy_decode`` and the
    batch-1 reference, zero behavior change.  Stochastic samplers run
    inside the SAME compiled step kernel using the rng key the step
    already carried dead: the kernel folds a per-step tick into the
    engine's base key, so join/leave churn never retraces and a fixed
    ``seed`` replays bitwise.

    Note the reproducibility boundary: greedy output is independent of
    slot-pool company (the row-local contract); a stochastic sampler's
    draws additionally depend on WHICH step ticks and slot a request
    rode through, so they replay only against the same engine history.
    """
    greedy = False

    def sample(self, key, logits):
        """jax-land: (slots, vocab) logits + folded PRNG key -> (slots,)
        sampled ids (cast back to the logits dtype — the token vector
        rides the same float pipeline the argmax head fed)."""
        raise NotImplementedError

    def spec_logits(self, logits):
        """jax-land: raw logits -> the sampler's log-space
        distribution (temperature scaling, top-k masking) — what
        speculative rejection sampling verifies draft proposals
        against.  Must be the same transform :meth:`sample` draws
        from, applied identically to target and draft logits, or the
        emitted distribution drifts from the single-token engine's.
        Greedy samplers never call this (acceptance is exact argmax
        prefix match)."""
        raise MXNetError(
            "%s does not support speculative decode: implement "
            "spec_logits() (the distribution rejection sampling "
            "verifies against)" % type(self).__name__)

    def describe(self):
        return {"kind": type(self).__name__}


class GreedySampler(Sampler):
    """The default argmax head — spliced into the step GRAPH itself
    (exactly the pre-sampler engine), so greedy decode stays bitwise-
    identical to ``greedy_decode`` and compiles the identical program."""
    greedy = True

    def describe(self):
        return {"kind": "greedy"}


class TemperatureSampler(Sampler):
    """Temperature (optionally top-k-truncated) categorical sampling.

    ``logits / temperature`` -> optional top-k mask (everything below
    the k-th logit pinned to -inf) -> one Gumbel-max categorical draw
    per slot (``jax.random.categorical``).  ``top_k=1`` degenerates to
    argmax whatever the key — the cheap sanity anchor tests pin.
    ``seed`` fixes the engine's base key for reproducible replays;
    None draws it from the process rng stream.
    """

    def __init__(self, temperature=1.0, top_k=None, seed=None):
        if temperature <= 0:
            raise MXNetError("TemperatureSampler: temperature must be "
                             "> 0, got %r (top_k=1 IS argmax)"
                             % (temperature,))
        if top_k is not None and int(top_k) < 1:
            raise MXNetError("TemperatureSampler: top_k must be >= 1")
        self.temperature = float(temperature)
        self.top_k = None if top_k is None else int(top_k)
        self.seed = seed

    def sample(self, key, logits):
        import jax
        return jax.random.categorical(key, self.spec_logits(logits),
                                      axis=-1).astype(logits.dtype)

    def spec_logits(self, logits):
        import jax
        import jax.numpy as jnp
        z = logits / self.temperature
        if self.top_k is not None and self.top_k < z.shape[-1]:
            kth = jax.lax.top_k(z, self.top_k)[0][..., -1:]
            z = jnp.where(z < kth, -jnp.inf, z)
        return z

    def describe(self):
        return {"kind": "temperature", "temperature": self.temperature,
                "top_k": self.top_k, "seed": self.seed}


class DecodeResult(object):
    """What a decode future resolves to: the generated token ids plus
    how generation ended.

    ``finish_reason`` is one of ``"eos"`` (the eos id was sampled),
    ``"length"`` (max_new_tokens or the slot's max_len capacity),
    ``"deadline"`` (the request's deadline passed mid-flight — tokens
    holds the PARTIAL generation), ``"closed"`` (engine shut down
    without drain), or ``"error"`` (the request's device replica
    failed mid-generation and was retired — tokens holds the PARTIAL
    generation; co-resident replicas keep serving).  ``expired``
    mirrors the deadline case.
    """
    __slots__ = ("tokens", "finish_reason", "n_steps", "prompt_len")

    def __init__(self, tokens, finish_reason, n_steps=0, prompt_len=0):
        self.tokens = np.asarray(tokens, dtype=np.int64)
        self.finish_reason = finish_reason
        self.n_steps = n_steps
        self.prompt_len = prompt_len

    @property
    def expired(self):
        return self.finish_reason == "deadline"

    def __len__(self):
        return len(self.tokens)

    def __repr__(self):
        return ("<DecodeResult %d tokens, %s>"
                % (len(self.tokens), self.finish_reason))


class DecodeRequest(Request):
    """One decode request: a prompt plus generation bookkeeping the
    scheduler mutates as the request moves queue -> slot -> done."""
    __slots__ = ("prompt", "max_new", "tokens", "prompt_i", "slot",
                 "t_join", "n_steps", "t_first_tok", "t_last_tok",
                 "on_token", "sse_id", "uflops")

    def __init__(self, prompt, max_new, future, deadline=None,
                 trace=None, on_token=None, sse_id=None):
        super().__init__({}, ("__decode__",), future, deadline=deadline,
                         trace=trace)
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        # per-request SSE stream key (ROADMAP item 4 residual): with a
        # client-supplied request id, every generated token is ALSO
        # published to the /events EventHub as a `decode.token` event
        # keyed by it — the hub's bounded replay ring gives
        # Last-Event-ID resume for free.  None = no HTTP surface, the
        # pre-SSE engine byte-for-byte.
        self.sse_id = sse_id
        # per-token streaming hook (ROADMAP 4a): called from the slot
        # loop with each generated token id, in generation order — the
        # exact greedy_decode prefix.  A raising callback evicts ONLY
        # its own request (the future fails with the exception; co-
        # residents keep generating).  SSE per-request streaming stays
        # a follow-up; this is its engine-side seam.
        self.on_token = on_token
        self.tokens = []            # generated ids (host mirror)
        self.prompt_i = 0           # next prompt token to teacher-force
        self.slot = None
        self.t_join = None
        self.n_steps = 0
        # decode latency anatomy: first/last generated-token stamps
        # feed the TTFT and inter-token (TPOT) histograms
        self.t_first_tok = None
        self.t_last_tok = None
        # useful-FLOPs accumulator for tenant accounting (goodput.py):
        # each dispatch this request rides adds its share; flushed to
        # the tenant series when the slot finishes
        self.uflops = 0


class StepProgram(object):
    """The persistent compiled decode step over a fixed slot pool.

    Wraps ``step_sym`` (outputs ``[logits] + next_states``) with a
    greedy ``argmax`` head and compiles it ONCE at batch extent
    ``num_slots`` — iteration-level scheduling never changes a shape,
    so ``trace_count`` is the whole compile story: the step kernel,
    plus one tiny row-write kernel per distinct state shape (slot
    join/leave scatter), all exercised by ``DecodeEngine.warmup``.

    Per-slot state lives as jax device buffers between calls; on
    non-CPU backends the state arguments are DONATED to the dispatch,
    so the pool is updated in place in HBM (the O(1) cache layout of
    arxiv 2603.09555 — no growth, no re-layout, no host round-trip).
    """

    def __init__(self, step_sym, arg_params, aux_params, state_info,
                 num_slots, token_name="token", pos_name="pos",
                 valid_name="valid", ctx=None, dtype=np.float32,
                 sampler=None, aot=None, plan=None, spec=None):
        import jax
        import jax.numpy as jnp
        from ..context import cpu
        from ..executor import build_graph_fn, _count_xla_trace
        from .. import symbol as sym
        from . import spec as _spec_mod
        self._ctx = ctx or cpu()
        # speculative draft-k-verify (serving/spec.py, ISSUE 15): with
        # a SpecConfig the ONE compiled program per replica widens —
        # k+1 draft steps and k+1 target steps unroll in-graph, the
        # accept logic picks the committed prefix, and the commit
        # graph (blend chain or the selected _cache_write_rows
        # scatter) writes only accepted rows into the ORIGINAL cache.
        # None = the single-token program byte-for-byte.
        self._spec = spec
        # model-parallel decode (parallel/mesh.py ShardingPlan): params
        # upload as one sharded device_put each, per-slot state buffers
        # lay out under the plan's state_rules (a KV cache's feature
        # axis shards over tp), and the persistent step compiles under
        # the resulting placement — continuous batching runs tensor-
        # parallel across the replica's device group.  None = the
        # single-device program byte-for-byte.
        self._plan = plan
        self._aot = aot if (aot is not None and aot.enabled) else None
        self.num_slots = int(num_slots)
        self._dtype = np.dtype(dtype)
        self.sampler = sampler if sampler is not None else GreedySampler()
        self.state_info = [dict(s) for s in state_info]
        self.state_names = [s["name"] for s in self.state_info]
        self.token_name = token_name
        if len(step_sym) != 1 + len(self.state_names):
            raise MXNetError(
                "decode step graph has %d outputs; expected 1 (logits) "
                "+ %d next-state outputs (state_info order)"
                % (len(step_sym), len(self.state_names)))
        if self._spec is not None:
            # the spec program needs per-position RAW logits (the
            # greedy head becomes a jnp.argmax with identical
            # semantics inside the accept logic — same impl, same
            # tie-breaking, same dtype cast as the argmax op)
            head = step_sym[0]
        elif self.sampler.greedy:
            # greedy keeps the in-graph argmax head: bitwise-pinned
            # against greedy_decode, identical compiled program
            head = sym.argmax(step_sym[0], axis=1,
                              name="__decode_sample__")
        else:
            # stochastic samplers take the raw logits into the kernel
            # and sample there with the (formerly dead) rng key
            head = step_sym[0]
        self._serve_sym = sym.Group(
            [head] + [step_sym[i]
                      for i in range(1, len(step_sym))])
        arg_names = self._serve_sym.list_arguments()
        aux_names = self._serve_sym.list_auxiliary_states()
        if token_name not in arg_names:
            raise MXNetError("decode step graph has no %r input "
                             "(token_name); arguments: %s"
                             % (token_name, arg_names))
        missing = [n for n in self.state_names if n not in arg_names]
        if missing:
            raise MXNetError("decode step graph is missing state "
                             "input(s) %s" % missing)
        self.pos_name = pos_name if pos_name in arg_names else None
        self.valid_name = valid_name if valid_name in arg_names else None
        feeds = set([token_name] + self.state_names)
        feeds.update(n for n in (self.pos_name, self.valid_name) if n)
        lacking = [n for n in arg_names
                   if n not in feeds and n not in (arg_params or {})]
        if lacking:
            raise MXNetError("StepProgram: params missing for %s"
                             % lacking)
        order = list(arg_names) + list(aux_names)
        self._template = [None] * len(order)
        for i, n in enumerate(order):
            if n in feeds:
                continue
            src = arg_params if n in (arg_params or {}) else aux_params
            if self._plan is not None:
                self._template[i] = self._plan.put_param(n, src[n]._data)
            else:
                self._template[i] = src[n].as_in_context(self._ctx)._data
        self._feed_pos = {n: order.index(n) for n in feeds}
        gf = build_graph_fn(self._serve_sym, arg_names, aux_names)
        if gf.stochastic:
            raise MXNetError(
                "decode step graph contains stochastic ops (Dropout, "
                "samplers): the persistent step must be deterministic "
                "— greedy decode parity and per-slot bitwise "
                "reproducibility both depend on it")
        self._trace_count = 0
        na = len(arg_names)
        n_t = len(order)
        state_pos = tuple(order.index(n) for n in self.state_names)
        _sampler = self.sampler
        # -------------------------------------------------- draft half
        # the draft model is a full second graph riding the same flat
        # argument vector: its params append to the template (uploaded
        # to this replica's device / sharded under its plan exactly
        # like the target's), its per-slot state buffers live in the
        # same states dict under prefixed keys, and its token/pos/
        # valid inputs are fed the SAME host vectors as the target's.
        self.draft_state_keys = []
        self._spec_cache_t = []         # (name, T) target cache states
        self._spec_cache_d = []         # (key, T) draft cache states
        if self._spec is not None:
            dspec = self._spec
            # idempotent: the engine builds the shared commit graph
            # once before any replica constructs; a directly-built
            # StepProgram(spec=...) gets the same build here instead
            # of a KeyError inside its first traced dispatch
            dspec.build(self.num_slots, self.state_info, self._dtype)
            dsym = sym.Group(list(dspec.draft_sym))
            d_args = dsym.list_arguments()
            d_auxs = dsym.list_auxiliary_states()
            if dspec.token_name not in d_args:
                raise MXNetError("draft graph has no %r input; "
                                 "arguments: %s"
                                 % (dspec.token_name, d_args))
            d_states = dspec.draft_state_names()
            missing = [n for n in d_states if n not in d_args]
            if missing:
                raise MXNetError("draft graph is missing state "
                                 "input(s) %s" % missing)
            if len(dsym) != 1 + len(d_states):
                raise MXNetError(
                    "draft graph has %d outputs; expected 1 (logits) "
                    "+ %d next-state outputs" % (len(dsym),
                                                 len(d_states)))
            self._d_tok = dspec.token_name
            self._d_pos = (dspec.pos_name
                           if dspec.pos_name in d_args else None)
            self._d_valid = (dspec.valid_name
                             if dspec.valid_name in d_args else None)
            d_feeds = set([self._d_tok] + d_states)
            d_feeds.update(n for n in (self._d_pos, self._d_valid) if n)
            d_order = list(d_args) + list(d_auxs)
            lacking = [n for n in d_order
                       if n not in d_feeds
                       and n not in dspec.draft_arg_params
                       and n not in dspec.draft_aux_params]
            if lacking:
                raise MXNetError("SpecConfig: draft params missing "
                                 "for %s" % lacking)
            self._template += [None] * len(d_order)
            for i, n in enumerate(d_order):
                if n in d_feeds:
                    continue
                src = (dspec.draft_arg_params
                       if n in dspec.draft_arg_params
                       else dspec.draft_aux_params)
                if self._plan is not None:
                    self._template[n_t + i] = self._plan.put_param(
                        n, src[n]._data)
                else:
                    self._template[n_t + i] = \
                        src[n].as_in_context(self._ctx)._data
            # absolute feed positions in the merged flat vector,
            # keyed by the engine-side draft state keys
            from .spec import _draft_key
            self._d_feed_pos = {}
            for n in d_feeds:
                key_n = _draft_key(n) if n in d_states else n
                self._d_feed_pos[key_n] = n_t + d_order.index(n)
            self.draft_state_keys = dspec.draft_keys()
            gf_d = build_graph_fn(dsym, d_args, d_auxs)
            if gf_d.stochastic:
                raise MXNetError("draft graph contains stochastic "
                                 "ops: the speculative step must be "
                                 "deterministic given its rng key")
            nda = len(d_args)
            d_state_pos = tuple(n_t + d_order.index(n)
                                for n in d_states)
            # commit structure: cache-declared states commit accepted
            # rows through the (possibly _cache_write_rows-selected)
            # commit graph; everything else selects the chain state
            # at the accepted count
            for info in self.state_info:
                if info.get("cache"):
                    if self.pos_name is None:
                        raise MXNetError(
                            "state %r is cache-declared but the step "
                            "graph has no %r input — a positional "
                            "cache commit needs the write position"
                            % (info["name"], pos_name))
                    self._spec_cache_t.append(
                        (info["name"], int(info["shape"][0])))
            for info in dspec.draft_state_info:
                if info.get("cache"):
                    if self._d_pos is None:
                        raise MXNetError(
                            "draft state %r is cache-declared but the "
                            "draft graph has no %r input"
                            % (info["name"], dspec.pos_name))
                    self._spec_cache_d.append(
                        (_draft_key(info["name"]),
                         int(info["shape"][0])))
            gf_commit = commit_args = None
            if dspec.commit_sym is not None:
                commit_args = dspec.commit_sym.list_arguments()
                gf_commit = build_graph_fn(dspec.commit_sym,
                                           commit_args, [])
            K = dspec.K
            cache_keys = set(k for k, _t in
                             self._spec_cache_t + self._spec_cache_d)

            def call_spec(key, tick, reset, spec_m, *flat):
                self._trace_count += 1
                _count_xla_trace()
                flat = list(flat)
                # join-time zeroing covers BOTH models' state rows
                for i in state_pos + d_state_pos:
                    s = flat[i]
                    r = reset.reshape((-1,) + (1,) * (s.ndim - 1))
                    flat[i] = jnp.where(r > 0, jnp.zeros((), s.dtype),
                                        s)
                token0 = flat[self._feed_pos[self.token_name]]
                pos0 = (flat[self._feed_pos[self.pos_name]]
                        if self.pos_name is not None else None)
                kstep = jax.random.fold_in(key, tick)
                # ---- draft chain: k proposals + one state-advancing
                # extra step (its proposal is discarded; it exists so
                # an all-accept window leaves the draft having
                # consumed every committed token)
                xs = [token0]
                d_chain = []
                cur = {kk: flat[self._d_feed_pos[kk]]
                       for kk in self.draft_state_keys}
                dlogits = []
                for j in range(K):
                    df = list(flat[n_t:])
                    df[self._d_feed_pos[self._d_tok] - n_t] = xs[j]
                    if self._d_pos is not None:
                        df[self._d_feed_pos[self._d_pos] - n_t] = \
                            flat[self._d_feed_pos[self._d_pos]] \
                            + jnp.float32(j)
                    for ix, kk in enumerate(self.draft_state_keys):
                        df[d_state_pos[ix] - n_t] = cur[kk]
                    outs_d, _ = gf_d(df[:nda], df[nda:], key, False)
                    dlogits.append(outs_d[0])
                    cur = {kk: outs_d[1 + ix] for ix, kk in
                           enumerate(self.draft_state_keys)}
                    d_chain.append(cur)
                    if j < K - 1:
                        if _sampler.greedy:
                            prop = jnp.argmax(outs_d[0], axis=1) \
                                .astype(outs_d[0].dtype)
                        else:
                            zq = _sampler.spec_logits(outs_d[0])
                            prop = jax.random.categorical(
                                jax.random.fold_in(kstep, 2 * j),
                                zq, axis=-1).astype(outs_d[0].dtype)
                        xs.append(prop)
                # ---- target chain: score all K positions
                t_chain = []
                tlogits = []
                cur_t = {n2: flat[self._feed_pos[n2]]
                         for n2 in self.state_names}
                for j in range(K):
                    tf = list(flat[:n_t])
                    tf[self._feed_pos[self.token_name]] = xs[j]
                    if self.pos_name is not None:
                        tf[self._feed_pos[self.pos_name]] = \
                            pos0 + jnp.float32(j)
                    for n2 in self.state_names:
                        tf[self._feed_pos[n2]] = cur_t[n2]
                    outs_t, _ = gf(tf[:na], tf[na:], key, False)
                    tlogits.append(outs_t[0])
                    cur_t = {n2: outs_t[1 + ix] for ix, n2 in
                             enumerate(self.state_names)}
                    t_chain.append(cur_t)
                # ---- accept
                if _sampler.greedy:
                    toks, a = _spec_mod.greedy_accept(xs, tlogits)
                else:
                    toks, a = _spec_mod.rejection_accept(
                        kstep, xs, tlogits, dlogits,
                        _sampler.spec_logits)
                count = jnp.where(spec_m > 0, a + 1.0, 1.0)
                idx = (count - 1.0).astype(jnp.int32)
                # ---- commit: caches write accepted rows into the
                # ORIGINAL buffers (post-reset), everything else
                # selects the chain candidate at the accepted count
                committed = {}
                for n2 in self.state_names:
                    if n2 not in cache_keys:
                        committed[n2] = _spec_mod.commit_select(
                            [st[n2] for st in t_chain], idx)
                for kk in self.draft_state_keys:
                    if kk not in cache_keys:
                        committed[kk] = _spec_mod.commit_select(
                            [st[kk] for st in d_chain], idx)
                if gf_commit is not None:
                    # both models' caches share one window start: the
                    # engine feeds the same host pos vector to both
                    # graphs' pos inputs
                    base_pos = pos0 if pos0 is not None \
                        else flat[self._d_feed_pos[self._d_pos]]
                    cvals = {"__spec_pos__": base_pos,
                             "__spec_count__": count}
                    for n2, T in self._spec_cache_t:
                        cvals["__spec_cache__%s" % n2] = \
                            flat[self._feed_pos[n2]]
                        cvals["__spec_rows__%s" % n2] = \
                            _spec_mod.gather_rows(
                                [st[n2] for st in t_chain],
                                base_pos, T)
                    for kk, T in self._spec_cache_d:
                        cvals["__spec_cache__%s" % kk] = \
                            flat[self._d_feed_pos[kk]]
                        cvals["__spec_rows__%s" % kk] = \
                            _spec_mod.gather_rows(
                                [st[kk] for st in d_chain],
                                base_pos, T)
                    outs_c, _ = gf_commit(
                        [cvals[a2] for a2 in commit_args], [], key,
                        False)
                    ci = 0
                    for n2, _T in self._spec_cache_t:
                        committed[n2] = outs_c[ci]
                        ci += 1
                    for kk, _T in self._spec_cache_d:
                        committed[kk] = outs_c[ci]
                        ci += 1
                return ([toks, count]
                        + [committed[n2] for n2 in self.state_names]
                        + [committed[kk]
                           for kk in self.draft_state_keys])

        def call(key, tick, reset, *flat):
            self._trace_count += 1      # runs once per XLA trace
            _count_xla_trace()
            # a joining slot's state is zeroed HERE, fused into the
            # step program (``reset`` is a per-slot 1/0 host vector):
            # a join costs no device dispatch of its own, unlike a
            # write_row scatter (~ms each on CPU jax) per join.
            # jnp.where, not multiply: stale rows may hold non-finite
            # values and 0*inf would leak NaN into the fresh state.
            flat = list(flat)
            for i in state_pos:
                s = flat[i]
                r = reset.reshape((-1,) + (1,) * (s.ndim - 1))
                flat[i] = jnp.where(r > 0, jnp.zeros((), s.dtype), s)
            outs, _ = gf(flat[:na], flat[na:], key, False)
            if not _sampler.greedy:
                # fold the per-step tick into the (formerly dead) key
                # INSIDE the jit: tick is a traced scalar, so churning
                # values never retrace, and the sampler's draws are a
                # pure function of (base key, tick, logits)
                k = jax.random.fold_in(key, tick)
                outs = [_sampler.sample(k, outs[0])] + list(outs[1:])
            return outs

        if self._spec is not None:
            call = call_spec
        donate = ()
        if jax.default_backend() != "cpu":
            # in-place HBM update of the slot pool: the old state
            # buffers are donated to the dispatch (CPU jax cannot
            # honor donation and would warn per compile).  Offsets
            # skip the (key, tick, reset[, spec]) leading args.
            lead = 4 if self._spec is not None else 3
            donate = tuple(lead + order.index(n)
                           for n in self.state_names)
            if self._spec is not None:
                donate += tuple(lead + self._d_feed_pos[kk]
                                for kk in self.draft_state_keys)
        # the persistent step kernel resolves lazily at the first step
        # when an AOT cache is configured (serving/aot_cache.py): a
        # warm entry deserializes with zero traces — the compiled
        # decode step of arxiv 2603.09555 is never compiled twice for
        # the same (graph, pool geometry, sampler policy, backend) —
        # while a cold one compiles through jax.export (the one trace
        # that would have happened anyway) and persists.  Donation
        # does NOT survive the round trip on its own, so the donate
        # spec is re-applied on the jit wrapper around the exported
        # program (resolve_kernel donate_argnums) — the in-place HBM
        # slot-pool update must hold whether the program was traced
        # fresh or loaded from disk.
        self._jit_kernel = jax.jit(call, donate_argnums=donate)
        self._donate = donate
        self._kernel = None if self._aot is not None else self._jit_kernel
        # the lazy resolution can be reached from two threads at once
        # (the replica scheduler's first step racing a rehab probe on
        # this program): serialize it so exactly one trace happens
        self._kernel_lock = named_lock("decode.kernel")
        self._graph_digest = None
        if self._aot is not None:
            from .aot_cache import graph_digest
            self._graph_digest = graph_digest(self._serve_sym)
            if self._spec is not None:
                # the compiled program is the whole widened step:
                # target graph x draft graph x commit graph x window
                # width — all four are program identity (toggling k or
                # swapping the draft must never hit a stale entry)
                self._graph_digest = "spec.k%d.%s.%s.%s" % (
                    self._spec.k, self._graph_digest,
                    self._spec.draft_digest,
                    self._spec.commit_digest or "none")
        self._tick = 0          # per-step sample counter (stochastic
        #                         samplers fold it into the key; dead
        #                         and DCE'd under the greedy head)
        seed = getattr(self.sampler, "seed", None)
        if seed is not None:
            self._key = jax.random.PRNGKey(int(seed))
        else:
            from .. import random as _random
            self._key = _random.next_key()  # greedy: dead input

        def set_row(buf, idx, row):
            self._trace_count += 1
            _count_xla_trace()
            return buf.at[idx].set(row)

        # one trace per distinct state shape; the slot index is a
        # traced scalar so churn across slots never retraces.  With an
        # AOT cache the per-shape kernels resolve through it too —
        # warmup()'s row-write traces must also pin to zero on a warm
        # restart, or the "0 compiles for previously-served buckets"
        # contract would leak through the scatter path.
        self._set_row_jit = jax.jit(set_row)
        self._row_kernels = {}
        self._jnp = jnp

    @property
    def trace_count(self):
        return self._trace_count

    def init_states(self):
        """Fresh all-zero slot-pool state buffers, committed to this
        program's device — with replica routing the pool must live on
        ITS replica's device from the first step (an uncommitted buffer
        would land on the default device and make the step a cross-
        device computation)."""
        import jax
        dev = None if self._plan is not None else self._ctx.jax_device()
        out = {}
        infos = list(self._state_infos())
        for key, info in infos:
            dt = np.dtype(info.get("dtype") or self._dtype)
            shape = (self.num_slots,) + tuple(info["shape"])
            if self._plan is not None:
                # sharded slot-pool layout: the plan's state_rules
                # decide which per-slot axes partition over the group.
                # Built from HOST zeros — a pool sized to fit only
                # when sharded must never be staged whole on one
                # device (device_put ships each shard's slice)
                out[key] = self._plan.put_state(
                    info["name"], np.zeros(shape, dtype=dt))
            else:
                out[key] = jax.device_put(
                    self._jnp.zeros(shape, dtype=dt), dev)
        return out

    def _state_infos(self, which="all"):
        """(engine state key, info) pairs over the requested model
        half: ``"all"`` (the slot pool's full state set), ``"target"``
        or ``"draft"``.  Draft states ride the merged dict under
        prefixed keys so a draft h-state never collides with a target
        one."""
        if which in ("all", "target"):
            for info in self.state_info:
                yield info["name"], info
        if self._spec is not None and which in ("all", "draft"):
            from .spec import _draft_key
            for info in self._spec.draft_state_info:
                yield _draft_key(info["name"]), info

    def _row_kernel(self, buf, idx, row):
        """The row-scatter kernel for one (buffer, row) signature,
        resolved through the AOT cache when one is configured.  The
        graph component is a fixed tag — ``buf.at[idx].set(row)`` is
        the same program whatever engine asks — so entries are shared
        across engines and model architectures."""
        if self._aot is None:
            return self._set_row_jit
        # the sharded layout is part of the program identity: two state
        # buffers of one shape whose state_rules place them differently
        # must neither share a memoized kernel nor hit each other's
        # universal entries (the flat signature carries shapes/dtypes
        # only, so the placement rides the graph tag)
        shard = ("" if self._plan is None
                 else "|%s" % (getattr(getattr(buf, "sharding", None),
                                       "spec", None),))
        sig = (tuple(buf.shape), str(np.dtype(buf.dtype)),
               tuple(np.shape(row)),
               str(np.dtype(getattr(row, "dtype", None)
                            or np.asarray(row).dtype)), shard)
        kernel = self._row_kernels.get(sig)
        if kernel is None:
            from .aot_cache import resolve_kernel
            kernel, _src = resolve_kernel(
                self._aot, self._set_row_jit, "decode_set_row",
                "jnp_at_set_v1" + shard, [buf, idx, row], universal=True)
            self._row_kernels[sig] = kernel
        return kernel

    def _ensure_kernel(self, reset, flat, spec_m=None):
        """Resolve the persistent step kernel at the first dispatch
        (the argument avals are only concrete here): AOT-cache hit
        loads the serialized program with zero traces; a miss compiles
        once through jax.export and persists it.  Double-checked under
        a lock: the scheduler's first step and a rehab probe may race
        here, and exactly one resolution must win."""
        if self._kernel is None:
            with self._kernel_lock:
                if self._kernel is None:
                    from .aot_cache import resolve_kernel
                    lead = [self._key, np.int32(0), reset]
                    if spec_m is not None:
                        lead.append(spec_m)
                    kernel, _src = resolve_kernel(
                        self._aot, self._jit_kernel, "decode_step",
                        self._graph_digest,
                        lead + list(flat),
                        donate_argnums=self._donate)
                    self._kernel = kernel
        return self._kernel

    def write_row(self, states, slot, rows):
        """Scatter per-slot state rows (host or device arrays) into
        ``slot`` of every buffer named in ``rows``; returns the updated
        state dict.  The index is passed as a traced scalar — one
        compile per state shape, ever."""
        idx = self._jnp.asarray(slot, self._jnp.int32)
        out = dict(states)
        for name, row in rows.items():
            out[name] = self._row_kernel(out[name], idx, row)(
                out[name], idx, row)
        return out

    def zero_row(self, states, slot, which="all"):
        """Zero one slot's rows in every state buffer (a joining
        request must never inherit the previous occupant's state).
        ``which="draft"`` zeroes only the draft model's rows — the
        prefill commit path writes REAL target rows but the draft
        (which never saw the prompt) must start the generation cold,
        not from a dead request's leftovers."""
        rows = {}
        for key, info in self._state_infos(which):
            dt = np.dtype(info.get("dtype") or self._dtype)
            rows[key] = np.zeros(tuple(info["shape"]), dtype=dt)
        return self.write_row(states, slot, rows)

    def step(self, tokens, pos, valid, states, reset=None):
        """One decode iteration over the whole pool.  ``tokens``/
        ``pos``/``valid`` are host float32 vectors of length
        ``num_slots``; ``states`` the device buffers from
        :meth:`init_states`/previous steps.  ``reset`` optionally
        marks slots (1/0) whose state rows must read as fresh zeros
        this step — how a join clears the previous occupant's rows
        without a single extra device dispatch.  Returns (sampled ids
        as a host float vector, new state dict) — the only
        device->host traffic is the id vector."""
        if self._spec is not None:
            raise MXNetError("this StepProgram compiled a speculative "
                             "draft-k-verify step: dispatch through "
                             "step_spec()")
        if reset is None:
            reset = np.zeros((self.num_slots,), np.float32)
        flat = self._build_flat(tokens, pos, valid, states)
        kernel = self._ensure_kernel(reset, flat)
        self._tick = (self._tick + 1) & 0x7fffffff
        outs = kernel(self._key, np.int32(self._tick), reset, *flat)
        new_states = {name: outs[1 + i]
                      for i, name in enumerate(self.state_names)}
        return np.asarray(outs[0]), new_states

    def _build_flat(self, tokens, pos, valid, states):
        """Assemble the full flat argument vector: params from the
        template, the shared token/pos/valid host vectors into BOTH
        models' feed slots, every state buffer at its position."""
        flat = list(self._template)
        flat[self._feed_pos[self.token_name]] = tokens
        if self.pos_name is not None:
            flat[self._feed_pos[self.pos_name]] = pos
        if self.valid_name is not None:
            flat[self._feed_pos[self.valid_name]] = valid
        for name in self.state_names:
            flat[self._feed_pos[name]] = states[name]
        if self._spec is not None:
            flat[self._d_feed_pos[self._d_tok]] = tokens
            if self._d_pos is not None:
                flat[self._d_feed_pos[self._d_pos]] = pos
            if self._d_valid is not None:
                flat[self._d_feed_pos[self._d_valid]] = valid
            for key in self.draft_state_keys:
                flat[self._d_feed_pos[key]] = states[key]
        return flat

    def step_spec(self, tokens, pos, valid, spec, states, reset=None):
        """One speculative iteration over the whole pool: up to
        ``k + 1`` tokens commit per slot per dispatch.  ``spec`` marks
        the slots eligible for speculation (generating, past their
        prompt) — ineligible slots commit exactly ONE position, the
        plain step's semantics, so teacher forcing and dead slots ride
        the wider program unchanged.  Returns ``(tokens, counts,
        new_states)``: a ``(slots, k+1)`` token matrix, the per-slot
        committed counts, and the committed state dict."""
        if self._spec is None:
            raise MXNetError("step_spec() needs a StepProgram built "
                             "with a SpecConfig")
        if reset is None:
            reset = np.zeros((self.num_slots,), np.float32)
        flat = self._build_flat(tokens, pos, valid, states)
        kernel = self._ensure_kernel(reset, flat, spec_m=spec)
        self._tick = (self._tick + 1) & 0x7fffffff
        outs = kernel(self._key, np.int32(self._tick), reset, spec,
                      *flat)
        keys = list(self.state_names) + list(self.draft_state_keys)
        new_states = {key: outs[2 + i] for i, key in enumerate(keys)}
        return np.asarray(outs[0]), np.asarray(outs[1]), new_states

    def probe_step(self):
        """One fixed-key, fixed-tick dispatch over an all-zero scratch
        pool — the bitwise probe replica probation rides on: two
        programs built from the same graph (traced fresh OR loaded
        from the AOT cache) must return exactly equal outputs here
        before a rehabilitated replica may take traffic.  Uses a
        constant PRNGKey and tick so stochastic samplers compare
        deterministically, touches neither ``self._tick`` nor any live
        slot state, and compiles nothing a warmed program has not
        already compiled."""
        import jax
        z = np.zeros((self.num_slots,), np.float32)
        states = self.init_states()
        flat = self._build_flat(z, z, z, states)
        if self._spec is not None:
            kernel = self._ensure_kernel(z, flat, spec_m=z)
            outs = kernel(jax.random.PRNGKey(0), np.int32(0), z, z,
                          *flat)
        else:
            kernel = self._ensure_kernel(z, flat)
            outs = kernel(jax.random.PRNGKey(0), np.int32(0), z, *flat)
        return [np.asarray(o) for o in outs]

    def sample_tokens(self, logits):
        """Host-side sampling of a ``(rows, vocab)`` logits array with
        this program's sampler — the bucketed-prefill path's first
        token (the prefill program returns raw logits for non-greedy
        samplers; each call burns one tick so prefill draws never
        collide with step draws)."""
        logits = np.asarray(logits)
        if self.sampler.greedy:
            return np.argmax(logits, axis=-1).astype(np.float32)
        import jax
        self._tick = (self._tick + 1) & 0x7fffffff
        k = jax.random.fold_in(self._key, np.int32(self._tick))
        return np.asarray(self.sampler.sample(k, self._jnp.asarray(
            logits, dtype=self._jnp.float32)))


def greedy_decode(program, prompt, max_new_tokens, eos_id=None,
                  max_len=None):
    """Reference single-request greedy decode: teacher-force the prompt
    through ``program`` one token per step, then feed each argmax
    sample back, alone in slot 0.  This is the bitwise ground truth
    the continuous-batching engine is held to (tests/test_decode.py):
    whatever company a request keeps in the slot pool, its tokens must
    equal this loop's output exactly."""
    states = program.init_states()
    n = program.num_slots
    tokens = np.zeros((n,), np.float32)
    pos = np.zeros((n,), np.float32)
    valid = np.zeros((n,), np.float32)
    valid[0] = 1.0
    prompt = list(prompt)
    if not prompt:
        raise MXNetError("greedy_decode needs a non-empty prompt")
    tokens[0] = prompt[0]
    out, p, i = [], 0, 1
    while len(out) < max_new_tokens:
        if max_len is not None and p >= max_len:
            break
        pos[0] = p
        sampled, states = program.step(tokens, pos, valid, states)
        p += 1
        if i < len(prompt):             # still consuming the prompt
            tokens[0] = prompt[i]
            i += 1
            continue
        tok = int(sampled[0])
        out.append(tok)
        tokens[0] = sampled[0]
        if eos_id is not None and tok == eos_id:
            break
    return np.asarray(out, dtype=np.int64)


class _DecodeTelemetry(object):
    """Decode engine's instrument bundle (mxnet_serve_decode_*), built
    only when telemetry is enabled.  Shares the admission families
    with the one-shot engine (AdmissionController reads ``admitted``/
    ``rejected``/``shed``/``expired``/``queue_depth`` off this object)
    so both engine kinds aggregate into one serving picture; decode-
    specific series follow the PR 3-7 idiom — shared counters, per-
    engine gauges reclaimed at close()."""

    def __init__(self, engine):
        reg = _telemetry.registry()
        self.engine_label = str(next(_ENGINE_SEQ))
        self.closed = False
        self.requests = reg.counter(
            "mxnet_serve_requests_total", "serving requests submitted")
        self.admitted = reg.counter(
            "mxnet_serve_admitted_total", "requests admitted")
        self.rejected = reg.counter(
            "mxnet_serve_rejected_total",
            "requests rejected with QueueFullError backpressure")
        self.shed = reg.counter(
            "mxnet_serve_shed_total",
            "requests shed under the shed-oldest overload policy")
        self.regulator_shed = reg.counter(
            "mxnet_serve_regulator_shed_total",
            "requests shed cost-aware by the overload regulator's "
            "tightened queue limit — deliberately NOT part of the "
            "queue-saturation burn numerator (the regulator's own "
            "sheds must not re-fire the rule it is resolving)")
        self.expired = reg.counter(
            "mxnet_serve_expired_total",
            "requests expired past their deadline while queued")
        queue_depth_fam = reg.gauge(
            "mxnet_serve_queue_depth",
            "pending admission-queue depth per engine",
            labelnames=("engine",))
        self.queue_depth = queue_depth_fam.labels(
            engine=self.engine_label)
        self.tokens = reg.counter(
            "mxnet_serve_decode_tokens_total",
            "tokens generated by continuous-batching decode engines")
        self.steps = reg.counter(
            "mxnet_serve_decode_steps_total",
            "decode step-program dispatches (each steps every live "
            "slot once)")
        # slot-occupancy decomposition of every step dispatch (ISSUE
        # 18 satellite): the persistent step always computes num_slots
        # rows, so each dispatch splits exactly into live rows (a
        # seated request advanced) and dead rows (masked slots riding
        # along).  Scraped counters, not occupancy-gauge inference —
        # the goodput plane's dead-slot FLOPs class divides out of
        # these same integers.
        self.slot_steps_live = reg.counter(
            "mxnet_serve_decode_live_slot_steps_total",
            "slot-steps computed for LIVE slots (a seated request's "
            "row advanced one position) across decode step dispatches")
        self.slot_steps_dead = reg.counter(
            "mxnet_serve_decode_dead_slot_steps_total",
            "slot-steps computed for DEAD slots (valid=0 rows riding "
            "the fixed-extent persistent step) across decode step "
            "dispatches")
        # coalesced-prefill element split, per prompt bucket: live =
        # real prompt positions, padded = the pow2 batch extent times
        # the bucket length (what the program actually computed) minus
        # live.  Bounded cardinality: one series per configured bucket.
        self.prefill_live_elems = reg.counter(
            "mxnet_serve_decode_prefill_live_elements_total",
            "prompt positions carrying real tokens in coalesced "
            "prefill dispatches, per prompt bucket",
            labelnames=("bucket",))
        self.prefill_padded_elems = reg.counter(
            "mxnet_serve_decode_prefill_padded_elements_total",
            "padding positions (batch-row and sequence overhang) in "
            "coalesced prefill dispatches, per prompt bucket",
            labelnames=("bucket",))
        self._prefill_elem_handles = {}
        self.joins = reg.counter(
            "mxnet_serve_decode_joins_total",
            "requests that joined the running decode batch (slot "
            "assigned between steps — never a retrace)")
        self.steals = reg.counter(
            "mxnet_serve_decode_steals_total",
            "routed-but-unseated requests STOLEN by a sibling replica "
            "with free slots (cross-replica work stealing: a request "
            "queued behind a full pool re-offers instead of waiting "
            "out its pinned replica's generations)")
        self.leaves = reg.counter(
            "mxnet_serve_decode_leaves_total",
            "requests that left the decode batch, by how generation "
            "ended (eos / length / deadline / closed / cancelled)",
            labelnames=("reason",))
        # label handles resolved ONCE: .labels() does registry work
        # per call, and leaves are hot-path (one per finished request)
        self._leave = {r: self.leaves.labels(reason=r)
                       for r in ("eos", "length", "deadline", "closed",
                                 "cancelled")}
        self.evictions = reg.counter(
            "mxnet_serve_decode_evictions_total",
            "slot-resident requests evicted mid-generation by their "
            "deadline: the future resolves with the PARTIAL tokens "
            "and expired=True, and the slot frees for queued work")
        self.step_ms = reg.histogram(
            "mxnet_serve_decode_step_ms",
            "wall time of one decode iteration (deadline sweep + step "
            "dispatch + host bookkeeping), per engine and device "
            "replica",
            labelnames=("engine", "replica"),
            buckets=_telemetry.LATENCY_MS_BUCKETS)
        # per-request tail latency the tokens/s counter cannot see
        # (the 2603.09555 O(1)-per-token framing is throughput-only):
        # TTFT = submit -> first generated token (queue wait + prefill
        # + first step), TPOT = mean inter-token gap over a finished
        # request's generation.  Engine-labeled so co-resident engines
        # keep distinct tails AND the series reclaim at close().
        ttft_fam = reg.histogram(
            "mxnet_serve_decode_ttft_seconds",
            "time to first token: submit -> first generated token id "
            "(queue wait + prefill + first step), per decode engine",
            labelnames=("engine",),
            buckets=_telemetry.LATENCY_S_BUCKETS)
        self.ttft = ttft_fam.labels(engine=self.engine_label)
        tpot_fam = reg.histogram(
            "mxnet_serve_decode_tpot_seconds",
            "inter-token latency: mean gap between consecutive "
            "generated tokens per finished request (>= 2 tokens), per "
            "decode engine",
            labelnames=("engine",),
            buckets=_telemetry.LATENCY_S_BUCKETS)
        self.tpot = tpot_fam.labels(engine=self.engine_label)
        # speculative decode plane (ISSUE 15): counters + per-engine
        # accept-rate histogram + tokens-per-step gauge, registered
        # ONLY for spec engines (a k=0 engine's scrape is byte-
        # identical to the pre-spec engine's) and reclaimed at close
        self.spec_drafted = None
        self._spec_fams = ()
        if getattr(engine, "_spec_k", 0):
            self.spec_drafted = reg.counter(
                "mxnet_serve_decode_spec_drafted_total",
                "draft tokens proposed by speculative decode steps "
                "(k per spec-eligible slot per dispatch)")
            self.spec_accepted = reg.counter(
                "mxnet_serve_decode_spec_accepted_total",
                "draft tokens ACCEPTED by target verification — the "
                "tokens that cost one target dispatch for k+1 "
                "positions instead of one dispatch each")
            self.spec_rejected = reg.counter(
                "mxnet_serve_decode_spec_rejected_total",
                "draft tokens rejected by target verification "
                "(speculative work thrown away)")
            spec_accept_fam = reg.histogram(
                "mxnet_serve_decode_spec_accept_rate",
                "per-dispatch draft acceptance fraction "
                "(accepted / drafted over the step's spec-eligible "
                "slots), per decode engine",
                labelnames=("engine",),
                buckets=_telemetry.RATIO_BUCKETS)
            self.spec_accept = spec_accept_fam.labels(
                engine=self.engine_label)
            spec_tps_fam = reg.gauge(
                "mxnet_serve_decode_spec_tokens_per_step",
                "mean committed tokens PER SLOT per speculative step "
                "over the engine lifetime (1.0 = no speculative win; "
                "the ceiling is k+1 — occupancy does not move this "
                "number), per decode engine",
                labelnames=("engine",))
            self.spec_tps = spec_tps_fam.labels(
                engine=self.engine_label)
            self._spec_fams = (spec_accept_fam, spec_tps_fam)
        self.slots_fam = reg.gauge(
            "mxnet_serve_decode_slots",
            "slot-pool capacity per decode engine and device replica",
            labelnames=("engine", "replica"))
        self.occupied_fam = reg.gauge(
            "mxnet_serve_decode_slots_occupied",
            "slots currently generating per decode engine and device "
            "replica — occupied/capacity is decode's batch-occupancy "
            "analog, and the router's most-free-slots signal",
            labelnames=("engine", "replica"))
        compile_fam = reg.gauge(
            "mxnet_serve_compile_count",
            "CachedOp trace counter — programs compiled so far, per "
            "engine", labelnames=("engine",))
        self.compile_count = compile_fam.labels(
            engine=self.engine_label)
        # replica plane: families defined ONCE in replica.py, shared
        # with the one-shot engine (engine ordinals are process-unique)
        # so /healthz renders one per-replica block over both kinds
        from .replica import replica_metric_families
        (replicas_fam, self.replica_healthy, self.replica_inflight,
         self.replica_failures,
         self.replica_shards) = replica_metric_families(reg)
        self.replicas_g = replicas_fam.labels(engine=self.engine_label)
        self.replicas_g.set(len(engine._replicas))
        for r in engine._replicas:
            r.tm_step_ms = self.step_ms.labels(
                engine=self.engine_label, replica=r.label)
            r.tm_failures = self.replica_failures.labels(
                engine=self.engine_label, replica=r.label)
            # per-shard identity under the replica label (static)
            self.replica_shards.labels(
                engine=self.engine_label, replica=r.label).set(
                len(r.plan.devices()) if r.plan is not None else 1)
        # persistent-AOT-cache traffic: same families the one-shot
        # bundle registers (engine ordinals are process-unique, so the
        # shared families aggregate into one fleet view)
        self.aot_fams = aot_metric_families(reg)
        # static memory planner pair (families shared with the
        # one-shot bundle): predicted set eagerly, measured created
        # lazily on the first successful allocator probe so CPU hosts
        # never publish a dead series
        mem_pred_fam, mem_meas_fam = memory_metric_families(reg)
        self.mem_predicted = mem_pred_fam.labels(
            engine=self.engine_label)
        self._mem_meas_fam = mem_meas_fam
        self._mem_measured = None
        self._mem_probe_ok = True
        self._engine_gauge_fams = (queue_depth_fam, compile_fam,
                                   ttft_fam, tpot_fam, replicas_fam,
                                   mem_pred_fam, mem_meas_fam) \
            + self._spec_fams
        self._replica_fams = (self.slots_fam, self.occupied_fam,
                              self.step_ms, self.replica_healthy,
                              self.replica_inflight,
                              self.replica_failures,
                              self.replica_shards) + self.aot_fams
        self._engine = weakref.ref(engine)
        reg.register_callback(self._refresh)

    def leave(self, reason):
        handle = self._leave.get(reason)
        (handle if handle is not None
         else self.leaves.labels(reason=reason)).inc()

    def prefill_elems(self, bucket, live, padded):
        """Count one coalesced prefill dispatch's element split under
        its prompt-bucket label (handles memoized: the bucket set is
        fixed at construction)."""
        h = self._prefill_elem_handles.get(bucket)
        if h is None:
            b = str(bucket)
            h = (self.prefill_live_elems.labels(bucket=b),
                 self.prefill_padded_elems.labels(bucket=b))
            self._prefill_elem_handles[bucket] = h
        if live:
            h[0].inc(live)
        if padded:
            h[1].inc(padded)

    def close(self):
        self.closed = True
        _telemetry.registry().unregister_callback(self._refresh)
        self._remove_engine_series()

    def _remove_engine_series(self):
        for fam in self._engine_gauge_fams:
            fam.remove(engine=self.engine_label)
        for fam in self._replica_fams:
            for values, _inst in fam.series():
                if values[0] == self.engine_label:
                    fam.remove(*values)

    def _refresh(self, reg):
        eng = self._engine()
        if eng is None:
            reg.unregister_callback(self._refresh)
            self._remove_engine_series()
            return
        self.compile_count.set(eng.compile_count)
        refresh_memory_gauges(self, eng)
        eff = getattr(eng, "_eff", None)
        if eff is not None:
            eff.refresh()
        if self.spec_drafted is not None:
            # GIL-atomic int reads: a collect-time callback must not
            # take scheduler locks
            steps, toks = eng._spec_slot_steps, eng._spec_accepted
            if steps:
                # committed tokens per slot per spec step = accepted
                # drafts + the one target token every step yields
                self.spec_tps.set((toks + steps) / float(steps))
        el = self.engine_label
        for r in eng._replicas:
            self.slots_fam.labels(engine=el,
                                  replica=r.label).set(eng.num_slots)
            self.occupied_fam.labels(
                engine=el, replica=r.label).set(r.occupied_count())
            self.replica_healthy.labels(
                engine=el, replica=r.label).set(1.0 if r.healthy
                                                else 0.0)
            self.replica_inflight.labels(
                engine=el, replica=r.label).set(r.inflight())


class DecodeEngine(object):
    """Continuous-batching autoregressive decode over one frozen step
    graph (module docstring has the architecture).

    Parameters
    ----------
    step_sym : Symbol with outputs ``[logits] + next_states``.
    arg_params, aux_params : trained weights (checkpoint artifacts).
    state_info : list of ``{"name", "shape"[, "dtype"]}`` — per-slot
        state buffers, in the order the step graph returns their next
        values (``BaseRNNCell.state_info`` shapes with the batch dim
        dropped; see ``begin_state_arrays`` for the cell-side analog).
    num_slots, max_len : slot-pool geometry (defaults from
        ``MXNET_DECODE_SLOTS`` / ``MXNET_DECODE_MAX_LEN``).
    eos_id : sampling this id ends a request with reason "eos".
    prefill_sym : optional prompt-consumption graph with outputs
        ``[logits_at_last_valid_position] + state_rows`` over arguments
        ``prefill_data_name`` ((1, T) prompt ids, T padded onto pow2
        buckets) and ``prefill_len_name`` ((1,) live prompt length the
        graph's masking keys on).  Either a length-polymorphic Symbol
        or a callable ``T -> Symbol`` (the BucketingModule idiom — an
        unrolled graph bakes its length in).  Compiled through the
        one-shot bucket path (ProgramCache, one program per pow2
        bucket); its state rows are scattered into the free slot.
        Without it, prompts are teacher-forced token-by-token through
        the running step batch (no extra programs).
    sampler : :class:`Sampler` hook for the token-selection head
        (default :class:`GreedySampler` — bitwise-pinned argmax).
        :class:`TemperatureSampler` runs temperature/top-k categorical
        draws inside the same compiled step using the rng key the
        step already carried dead.
    replicas : data-parallel device replicas (default
        ``MXNET_SERVE_REPLICAS``), each a full slot pool; requests land
        on the freest replica and pin there.  ``ctx`` may be a LIST of
        contexts naming the replica set verbatim.
    sharding : model-parallel plan spec (``parallel/mesh.py``; default
        ``MXNET_SERVE_SHARDING``).  Each replica's step program,
        prefill buckets, and per-slot state then span a
        ``prod(axes)``-device group — state_rules lay the KV cache out
        sharded, so continuous batching runs tensor-parallel.  A plan
        partitioning the SLOT axis is verdict-gated on the step
        graph's row-locality (``analysis.check_sharding_plan``);
        rejected plans refuse construction with a reason.
    """

    def __init__(self, step_sym, arg_params, aux_params, state_info,
                 token_name="token", pos_name="pos", valid_name="valid",
                 num_slots=None, max_len=None, eos_id=None,
                 prefill_sym=None, prefill_data_name="prompt",
                 prefill_len_name="plen",
                 max_queue=None, default_deadline_ms=None,
                 overload_policy=None, ctx=None, dtype=np.float32,
                 start=True, sampler=None, replicas=None, sharding=None,
                 draft_sym=None, draft_arg_params=None,
                 draft_aux_params=None, draft_state_info=None,
                 spec_k=None):
        from .. import config
        # chaos plan (serving/faults.py): see ServingEngine
        _faults.ensure_env_plan()
        if num_slots is None:
            num_slots = config.get("MXNET_DECODE_SLOTS")
        if max_len is None:
            max_len = config.get("MXNET_DECODE_MAX_LEN")
        # speculative draft-k-verify (ISSUE 15): k > 0 plus a draft
        # model widens every replica's step program to commit up to
        # k+1 tokens per slot per dispatch.  0 (the default) is the
        # single-token engine BYTE-IDENTICAL to the pre-spec code —
        # same programs, same AOT keys, same scrape — whatever draft
        # arguments were passed.
        if spec_k is None:
            spec_k = config.get("MXNET_DECODE_SPEC_K")
        spec_k = int(spec_k)
        if spec_k < 0:
            raise MXNetError("spec_k must be >= 0, got %d" % spec_k)
        if spec_k > 0 and draft_sym is None:
            raise MXNetError(
                "spec_k=%d needs a draft model: pass draft_sym= (and "
                "its params/state_info) — speculation verifies a "
                "cheap draft against the target, there is no draft "
                "to verify" % spec_k)
        self._spec_k = spec_k if draft_sym is not None else 0
        if self._spec_k and sampler is not None and not sampler.greedy \
                and type(sampler).spec_logits is Sampler.spec_logits:
            # refuse at construction, like every other spec contract
            # violation — raising inside the first traced dispatch
            # would ride the replica-failure path and retire healthy
            # replicas over a config error
            raise MXNetError(
                "speculative decode needs the sampler's verification "
                "distribution: %s must implement spec_logits() (see "
                "TemperatureSampler), or use spec_k=0"
                % type(sampler).__name__)
        if max_queue is None:
            max_queue = config.get("MXNET_SERVE_MAX_QUEUE")
        if default_deadline_ms is None:
            default_deadline_ms = config.get(
                "MXNET_SERVE_DEFAULT_DEADLINE_MS")
        if overload_policy is None:
            overload_policy = config.get("MXNET_SERVE_OVERLOAD_POLICY")
        if num_slots < 1:
            raise MXNetError("num_slots must be >= 1, got %d" % num_slots)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self._dtype = np.dtype(dtype)
        self._default_deadline_s = float(default_deadline_ms) / 1e3
        self._sampler = sampler if sampler is not None else GreedySampler()
        self.analysis_report = None
        self.step_verdict = None
        self.draft_verdict = None
        if config.get("MXNET_ANALYSIS_ON"):
            self.step_verdict, self.analysis_report = self._preflight(
                step_sym, state_info, token_name, pos_name,
                valid_name, config.get("MXNET_ANALYSIS_STRICT"),
                what="step")
            if self._spec_k:
                # the draft's states ride the SAME slot pool: a cross-
                # position draft would leak one request's (or a dead
                # slot's stale) values into a co-resident's proposals
                # — and through acceptance, into its LATENCY; greedy
                # content stays exact, but the soundness bar is the
                # same as the target's
                self.draft_verdict, _ = self._preflight(
                    draft_sym, draft_state_info or [], token_name,
                    pos_name, valid_name,
                    config.get("MXNET_ANALYSIS_STRICT"), what="draft")
        if self._spec_k:
            # head compatibility is NOT an analysis-suite opinion —
            # it only needs infer_shape, and a mismatched pair emits
            # garbage tokens silently (take_along_axis clamps under
            # jit) — so it refuses construction even with
            # MXNET_ANALYSIS_ON=0
            self._check_draft_heads(step_sym, draft_sym, state_info,
                                    draft_state_info or [],
                                    token_name, pos_name, valid_name)
        # fused-op selection (ISSUE 13): run the optimizer's kernel-
        # selection pipeline over the step graph BEFORE any program is
        # built, so StepProgram serves the optimized graph — the
        # one-hot-blend KV write becomes the O(d) _cache_write_row
        # scatter (ops/cache.py) when the verdict-gated plan accepts.
        # A rejected/crashed plan serves the step exactly as handed in.
        # With speculation the DRAFT graph rides the same pipeline —
        # its per-step KV write is as selectable as the target's.
        self.opt_plan = None
        self.selection = None
        self.draft_opt_plan = None
        if config.get("MXNET_SERVE_OPTIMIZE") \
                and config.get("MXNET_ANALYSIS_ON") \
                and config.get("MXNET_OPT_SELECT_KERNELS"):
            step_sym, self.opt_plan, self.selection = \
                self._optimize_step(step_sym, state_info, token_name,
                                    pos_name, valid_name, what="step")
            if self._spec_k:
                draft_sym, self.draft_opt_plan, _dsel = \
                    self._optimize_step(draft_sym,
                                        draft_state_info or [],
                                        token_name, pos_name,
                                        valid_name, what="draft")
        # the spec bundle every replica's StepProgram shares: draft
        # graph/params plus the ONE verdict-gated commit graph (built
        # here, not per replica — the selection decision is engine
        # policy, and it rides the AOT validity fingerprint)
        self._spec_cfg = None
        if self._spec_k:
            from .spec import SpecConfig
            self._spec_cfg = SpecConfig(
                self._spec_k, draft_sym,
                draft_arg_params=draft_arg_params,
                draft_aux_params=draft_aux_params,
                draft_state_info=draft_state_info,
                token_name=token_name, pos_name=pos_name,
                valid_name=valid_name)
            self._spec_cfg.build(self.num_slots, state_info, dtype)
        # model-parallel decode (ROADMAP item 1): the plan spec is
        # verdict-gated on the step graph's slot-axis row-locality —
        # a plan partitioning the slot axis of a cross-position (or
        # unanalyzed) step is rejected with a reason at construction,
        # exactly like every rewrite.  Param/state tensor-parallel
        # rules are placement-only and never gated.
        from ..analysis.sharding import gate_plan_spec
        # sharded plans gate the WIDER step like any program: with
        # speculation the compiled step contains both models, so a
        # slot-partitioning plan needs BOTH slot verdicts row-local
        # (either unproven/cross-position verdict fails the gate)
        gate_verdict = self.step_verdict
        if self._spec_k and gate_verdict == "row-local" \
                and self.draft_verdict != "row-local":
            gate_verdict = self.draft_verdict
        self.sharding_check, self._sharding_spec = gate_plan_spec(
            sharding, {"slot": gate_verdict}, "decode",
            "DecodeEngine")
        self._prefill_data_name = prefill_data_name
        self._prefill_len_name = prefill_len_name
        # coalesced bucketed prefill (ROADMAP 4b): joiners landing in
        # the same scheduler iteration share ONE prefill dispatch per
        # pow2 (batch, prompt) bucket instead of batch-1 each — the
        # direct TTFT lever at concurrency (decode_bench --prefill)
        self._coalesce = bool(config.get("MXNET_DECODE_COALESCE_PREFILL"))
        self._prefill_dispatches = 0
        # device replicas (serving/replica.py, ROADMAP 2a): each owns a
        # FULL slot pool — persistent step program + device-resident
        # state + prefill bucket caches, params uploaded once per
        # replica.  New requests land on the replica with the most free
        # slots and pin there for their whole generation (migrating a
        # request would ship its KV cache across devices); replicas == 1
        # is the pre-replica fast path, no router, no extra threads.
        #
        # Per-replica prefill goes through the one-shot bucket path:
        # one compiled program per pow2 prompt bucket, batch 1 (state
        # rows scatter into exactly one free slot).  ``prefill_sym`` is
        # either a length-polymorphic Symbol (one graph, ProgramCache's
        # shape keys are the buckets) or — the BucketingModule idiom,
        # since an unrolled graph bakes its length in — a callable
        # ``T -> Symbol`` invoked once per bucket.
        prefill_buckets = ()
        if prefill_sym is not None:
            buckets, b = [], 1
            top = _next_pow2(self.max_len)
            while b <= top:
                buckets.append(b)
                b <<= 1
            prefill_buckets = tuple(buckets)
        # coalesced prefill dispatches at pow2 BATCH buckets too (a
        # group of joiners pads up to the next one); serial mode only
        # ever dispatches batch 1 — warmup warms exactly this grid, so
        # the zero-warm-retrace contract covers every coalesced shape
        batches, bb = [], 1
        top_b = _next_pow2(self.num_slots)
        while bb <= top_b:
            batches.append(bb)
            bb <<= 1
        self._prefill_batches = tuple(batches) if self._coalesce else (1,)
        # static memory planner (analysis/memory.py): liveness-price
        # the whole warm set — step program at slot-pool shapes with
        # the pool's state-for-state donation spec gated for
        # soundness, draft step additively under spec, largest
        # prefill bucket plus the resident pool — against the device
        # budget BEFORE any compile.  Purely diagnostic: the engine
        # serves bitwise-identically with the planner off.
        self.memory_plan = None
        if config.get("MXNET_MEMORY_PLAN") \
                and config.get("MXNET_ANALYSIS_ON"):
            self._memory_preflight(
                step_sym, state_info, arg_params, aux_params,
                token_name, pos_name, valid_name, prefill_sym,
                prefill_buckets, draft_sym, draft_state_info,
                draft_arg_params, draft_aux_params,
                config.get("MXNET_ANALYSIS_STRICT"))
        # persistent AOT program cache (serving/aot_cache.py,
        # MXNET_AOT_CACHE_DIR): one per engine, shared by every
        # replica's step program, prefill buckets, and row-scatter
        # kernels — a restarted engine (or a rehabilitated replica)
        # loads warm instead of retracing.  The step verdict rides the
        # validity fingerprint (re-validated on load: drift rejects the
        # entry); the sampler policy — which shapes the compiled head —
        # rides the key, minus the runtime-only seed.
        from .aot_cache import AOTCache
        sampler_fp = {k: v for k, v in self._sampler.describe().items()
                      if k != "seed"}
        # spec policy rides the KEY (cross-k and cross-draft hits are
        # impossible by address) AND the validity fingerprint (below):
        # graph-invariant entries — prefill buckets, universal
        # row-scatter kernels — share one key across spec regimes, so
        # only the fingerprint protects them, and it must: toggling k
        # or swapping drafts REJECTS those entries (alertable "cold
        # start that should have been warm"), never serves a program
        # compiled under different spec conclusions.  Both components
        # are OMITTED when spec is off, so a pre-spec cache volume
        # stays warm across this upgrade.
        artifact = {"kind": "decode",
                    "step_verdict": self.step_verdict,
                    "selection": self.selection,
                    "optimizer": {
                        "accepted": (bool(self.opt_plan.accepted)
                                     if self.opt_plan is not None
                                     else None),
                        "nodes_before": (self.opt_plan.nodes_before
                                         if self.opt_plan is not None
                                         else None),
                        "nodes_after": (self.opt_plan.nodes_after
                                        if self.opt_plan is not None
                                        else None)},
                    # the memory plan's digest rides the validity
                    # fingerprint: a planner upgrade that moves the
                    # prediction re-prices warm entries instead of
                    # serving under stale capacity conclusions
                    "memory": (self.memory_plan.get("digest")
                               if self.memory_plan else None)}
        key_extra = {"engine_kind": "decode", "sampler": sampler_fp}
        if self._spec_cfg is not None:
            artifact["spec"] = dict(self._spec_cfg.describe(),
                                    draft_verdict=self.draft_verdict)
            key_extra["spec"] = {"k": self._spec_cfg.k,
                                 "draft": self._spec_cfg.draft_digest}
        # the fused-op selection outcome rides the validity FINGERPRINT
        # (not the key): flipping MXNET_OPT_SELECT_KERNELS between
        # restarts moves the fingerprint, so every entry the previous
        # selection regime wrote is REJECTED on load (alertable "cold
        # start that should have been warm") rather than any program
        # compiled under different analysis conclusions being served —
        # the step graph's own key also moves (its canonical form
        # changed), but graph-invariant entries (prefill buckets,
        # universal row-scatter kernels) are only protected by the
        # fingerprint (tests/test_decode_fastpath.py pins the reject)
        self._aot = AOTCache.from_config(
            artifact=artifact,
            key_extra=key_extra,
            # plan spec = the key's sharding component (residual b2):
            # sharded and unsharded step programs (or two plans) can
            # never hit each other's entries; same-plan replicas share
            sharding=self._sharding_spec or "none")
        # everything _new_replica needs, kept for probation re-warm
        # (rehabilitate): the param handles are the same NDArrays the
        # program caches already hold device copies of — no extra
        # host memory of consequence
        self._ctor = {"step_sym": step_sym, "arg_params": arg_params,
                      "aux_params": aux_params,
                      "state_info": state_info,
                      "token_name": token_name, "pos_name": pos_name,
                      "valid_name": valid_name, "dtype": dtype,
                      "prefill_sym": prefill_sym,
                      "prefill_buckets": prefill_buckets}
        self._replicas = []
        placements = resolve_replica_placements(replicas, ctx,
                                                self._sharding_spec)
        for i, (rctx, rplan) in enumerate(placements):
            self._replicas.append(self._new_replica(i, rctx, rplan))
        self._multi = len(self._replicas) > 1
        self._dr_lock = named_lock("decode.replica")
        self._dr_cond = named_condition("decode.replica", self._dr_lock)
        self._dr_stop = False
        self._slot_free = threading.Event()
        self._tm = (_DecodeTelemetry(self)
                    if _telemetry.enabled() else None)
        # unified fleet timeline (telemetry/timeline.py): cached ring
        # reference, None when the plane is off — the disabled path
        # appends nothing and decodes bitwise-identically
        self._tl = (_telemetry.timeline.get()
                    if _telemetry.timeline.enabled() else None)
        # serving efficiency plane (ISSUE 18): per-dispatch FLOPs
        # ledger + MFU/goodput gauges + per-tenant accounting.  Step
        # programs are priced ONCE here (memoized on the program);
        # prefill buckets price lazily in ProgramCache._plan_for.
        self._eff = None
        if self._tm is not None and _goodput.enabled():
            self._eff = _goodput.EngineEfficiency(
                "decode", self._tm.engine_label)
            for r in self._replicas:
                self._eff.add_replica(r.label, ctx=r.ctx)
                _goodput.price_step_program(r.program)
        if self._tm is not None and self._aot is not None:
            self._aot.bind_telemetry(*(
                fam.labels(engine=self._tm.engine_label)
                for fam in self._tm.aot_fams))
        self._trace_chain = (_telemetry.chain_from_config()
                             if self._tm is not None else None)
        self._owns_http_server = (_telemetry.server.engine_acquire()
                                  if self._tm is not None else False)
        self._adm = AdmissionController(
            max_queue=max_queue, overload_policy=overload_policy,
            wake_hint=self.num_slots * len(self._replicas),
            telemetry=self._tm)
        self._lock = named_lock("decode.engine")
        self._step_ms = collections.deque(maxlen=4096)
        self._lat_ms = collections.deque(maxlen=4096)
        self._steps = 0
        self._joins = 0
        self._steals = 0
        self._leaves = 0
        self._evictions = 0
        self._tokens_out = 0
        self._requests_served = 0
        self._spec_steps = 0        # dispatches with >=1 spec slot
        self._spec_slot_steps = 0   # per-slot spec steps (the
        #                             tokens-per-step denominator)
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._abort = False
        # history/alerting plane (engine.py has the full story): the
        # scheduler loop stamps a heartbeat, the engine registers for
        # flight-recorder stats() capture, default SLO rules cover the
        # decode plane (shared burn rates + per-engine zero-progress
        # watchdog), and the recorder sampler is refcounted.
        # Registered LAST — after the failure-prone slot-pool state
        # allocation — so a constructor that raises never holds a
        # rule, heartbeat, or recorder reference close() cannot drop.
        self._hb_t = time.monotonic()
        self._hb_busy = False
        self._owns_recorder = False
        self._alert_owner = None
        self._obs_name = None
        if self._tm is not None:
            self._obs_name = "decode.%s" % self._tm.engine_label
            _telemetry.recorder.register_heartbeat(self._obs_name,
                                                   self._heartbeat)
            _telemetry.recorder.register_engine(self._obs_name, self)
            self._owns_recorder = _telemetry.recorder.recorder_acquire()
            if config.get("MXNET_TELEMETRY_ALERTS"):
                self._alert_owner = \
                    _telemetry.register_engine_default_rules(
                        "decode", self._tm.engine_label,
                        aot=self._aot is not None)
        # self-healing control plane (ISSUE 12): see ServingEngine
        self._regulator = None
        if self._tm is not None and config.get("MXNET_REGULATOR"):
            from .regulator import Regulator
            self._regulator = Regulator(
                self._adm, engine_label=self._tm.engine_label,
                name=self._obs_name or "decode")
        self._sup_owner = False
        if config.get("MXNET_SUPERVISOR"):
            from . import supervisor as _supervisor
            _supervisor.engine_acquire(self,
                                       name=self._obs_name or "decode")
            self._sup_owner = True
        self._worker = None
        if start:
            self.start()

    # single-replica aliases: replica 0 IS the engine on the fast path,
    # and tests stage prefill failures by swapping these directly
    @property
    def _program(self):
        return self._replicas[0].program

    @property
    def _prefill_caches(self):
        return self._replicas[0].prefill_caches

    @_prefill_caches.setter
    def _prefill_caches(self, value):
        self._replicas[0].prefill_caches = value

    @property
    def _prefill_buckets(self):
        return self._replicas[0].prefill_buckets

    @_prefill_buckets.setter
    def _prefill_buckets(self, value):
        self._replicas[0].prefill_buckets = tuple(value)

    def _new_replica(self, index, rctx, plan=None):
        """Build one fully-formed DecodeReplica (step program + prefill
        caches, params uploaded to its device — or sharded across its
        plan's device group) from the construction state — used at
        engine construction AND by ``rehabilitate()``, which must
        rebuild a retired replica's programs from scratch (its donated
        state buffers may be consumed) but draws every compile from
        the AOT cache when one is configured."""
        from ..symbol import Symbol as _Symbol
        c = self._ctor
        prog = StepProgram(c["step_sym"], c["arg_params"],
                           c["aux_params"], c["state_info"],
                           self.num_slots,
                           token_name=c["token_name"],
                           pos_name=c["pos_name"],
                           valid_name=c["valid_name"],
                           ctx=rctx, dtype=c["dtype"],
                           sampler=self._sampler, aot=self._aot,
                           plan=plan, spec=self._spec_cfg)
        rep = DecodeReplica(index, rctx, prog, plan=plan)
        prefill_sym = c["prefill_sym"]
        if prefill_sym is not None:
            rep.prefill_buckets = c["prefill_buckets"]
            # Symbol is itself callable (compose), so "callable" alone
            # cannot distinguish the T -> Symbol builder idiom
            if not isinstance(prefill_sym, _Symbol) \
                    and callable(prefill_sym):
                for b in rep.prefill_buckets:
                    rep.prefill_caches[b] = self._build_prefill(
                        prefill_sym(b), c["arg_params"],
                        c["aux_params"], rctx, c["dtype"], prog, plan)
            else:
                shared = self._build_prefill(
                    prefill_sym, c["arg_params"], c["aux_params"],
                    rctx, c["dtype"], prog, plan)
                for b in rep.prefill_buckets:
                    rep.prefill_caches[b] = shared
        return rep

    def _build_prefill(self, psym, arg_params, aux_params, ctx, dtype,
                       program, plan=None):
        """Wrap one prefill graph with the sampling head and compile-
        once plumbing: outputs become [first sampled token id] + state
        rows under the greedy head, or [last-position logits] + state
        rows for stochastic samplers (the host then draws through
        ``StepProgram.sample_tokens`` so prefill uses the same sampler
        and key stream as the step)."""
        from .. import symbol as sym
        if len(psym) != 1 + len(program.state_names):
            raise MXNetError(
                "prefill graph has %d outputs; expected 1 (logits at "
                "the last valid position) + %d state rows"
                % (len(psym), len(program.state_names)))
        head = (sym.argmax(psym[0], axis=1,
                           name="__decode_prefill_sample__")
                if self._sampler.greedy else psym[0])
        wrapped = sym.Group(
            [head] + [psym[i] for i in range(1, len(psym))])
        return ProgramCache(
            wrapped, arg_params, aux_params,
            data_names=[self._prefill_data_name, self._prefill_len_name],
            ctx=ctx, dtype=dtype, aot=self._aot, aot_kind="prefill",
            plan=plan)

    # ---------------------------------------------------------- preflight
    def _preflight(self, step_sym, state_info, token_name, pos_name,
                   valid_name, strict, what="step"):
        """Construction-time soundness lint: the masked step must be
        row-local along the SLOT axis with state seeded pad-dirty
        (analysis.check_decode_step) — a cross-position step would let
        one request's (or a dead slot's stale) values bleed into a
        co-resident request's tokens.  Runs over the target step AND
        (speculative engines) the draft graph — both ride the same
        slot pool.  Returns (verdict, report)."""
        from ..analysis import check_decode_step, AnalysisError
        n = self.num_slots
        arg_names = set(step_sym.list_arguments())
        shapes = {token_name: (n,)}
        state_names = []
        for info in state_info:
            shapes[info["name"]] = (n,) + tuple(info["shape"])
            state_names.append(info["name"])
        for extra in (pos_name, valid_name):
            if extra in arg_names:
                shapes[extra] = (n,)
        verdict, report = check_decode_step(
            step_sym, shapes, state_names=state_names,
            valid_name=valid_name if valid_name in arg_names else None)
        if report.errors:
            if strict:
                report.raise_if_errors()
            warnings.warn("DecodeEngine: %s-graph verification "
                          "failed:\n%s" % (what, report.format()))
            return verdict, report
        if verdict == "cross-position":
            detail = "\n".join("  " + str(d) for d in report.warnings) \
                or "  (see report)"
            msg = ("[padding] DecodeEngine: %s graph is cross-"
                   "position along the SLOT axis — co-resident "
                   "requests (and stale state in freed slots) would "
                   "contaminate each other's tokens:\n%s"
                   % (what, detail))
            if strict:
                raise AnalysisError(msg)
            warnings.warn(msg + "\ncontinuing because "
                          "MXNET_ANALYSIS_STRICT=0; decoded output "
                          "WILL differ from single-request decode")
        return verdict, report

    def _memory_preflight(self, step_sym, state_info, arg_params,
                          aux_params, token_name, pos_name, valid_name,
                          prefill_sym, prefill_buckets, draft_sym,
                          draft_state_info, draft_arg_params,
                          draft_aux_params, strict):
        """OOM preflight + donation gate (analysis/memory.py).

        The step program is priced at slot-pool shapes with the pool's
        state-for-state donation spec — state ``i`` aliases output
        ``1+i``, exactly what StepProgram donates — and an UNSOUND
        donation (a state read by a node not ordered before its
        aliasing next-state write) is refused here with the node
        pinned, because the in-place update would clobber the buffer
        before its last read.  Speculative engines price the draft
        step additively: both models and both state pools are resident
        during a dispatch.  Prefill is priced at its largest
        (batch, prompt) bucket PLUS the resident slot pool (prefill
        runs while the pool lives; the pool is not among its inputs).
        Bytes divide along plan-partitioned axes.  Over budget warns
        naming the offending program and bytes — plus a max-slots-
        that-fit advisory — and ``MXNET_ANALYSIS_STRICT=1`` raises;
        either way the verdict lands before any compile."""
        from ..analysis import AnalysisError
        from ..analysis.memory import (plan_memory, plan_digest,
                                       device_memory_budget,
                                       format_bytes, shard_divisor)
        from ..symbol import Symbol as _Symbol
        try:
            n = self.num_slots
            spec = self._sharding_spec

            def price_step(sym_, infos, a_params, x_params):
                arg_names = set(sym_.list_arguments())
                shapes = {token_name: (n,)}
                donate, names = {}, []
                for i, info in enumerate(infos):
                    shapes[info["name"]] = (n,) + tuple(info["shape"])
                    names.append(info["name"])
                    donate[info["name"]] = 1 + i
                for extra in (pos_name, valid_name):
                    if extra in arg_names:
                        shapes[extra] = (n,)
                dtypes = {k: self._dtype for k in shapes}
                for src in (a_params or {}), (x_params or {}):
                    for k, v in src.items():
                        dt = getattr(v, "dtype", None)
                        if dt is not None:
                            dtypes.setdefault(k, np.dtype(dt))
                plan, _rep = plan_memory(sym_, shapes, dtypes=dtypes,
                                         sharding=spec, donate=donate,
                                         state_names=names)
                return plan

            plan = price_step(step_sym, state_info, arg_params,
                              aux_params)
            if not plan:
                return
            dplan = None
            if self._spec_k and draft_sym is not None:
                dplan = price_step(draft_sym, draft_state_info or [],
                                   draft_arg_params, draft_aux_params)
            # the slot pool the step's inputs already include —
            # (num_slots,) + state shape per state, divided along plan
            # state rules — stays resident under prefill too
            pool = 0
            for info in state_info:
                shp = (n,) + tuple(info["shape"])
                nbytes = int(np.prod(shp)) * self._dtype.itemsize
                pool += nbytes // shard_divisor(spec, info["name"],
                                                shp, kind="state")
            per_slot = pool // n

            def row(label, p):
                return {"program": label,
                        "peak_bytes": p["peak_bytes"],
                        "param_bytes": p["param_bytes"],
                        "transient_peak_bytes":
                            p["transient_peak_bytes"],
                        "inplace_savings_bytes":
                            p["inplace_savings_bytes"]}

            programs = [row("step", plan)]
            need = plan["peak_bytes"]
            offender = "step"
            donation = {"step": plan["donation"]}
            if dplan:
                programs.append(row("draft", dplan))
                need += dplan["peak_bytes"]
                offender = "step+draft"
                donation["draft"] = dplan["donation"]
            if prefill_sym is not None and prefill_buckets:
                b_top = max(prefill_buckets)
                bb = max(self._prefill_batches)
                psym = prefill_sym
                if not isinstance(psym, _Symbol) and callable(psym):
                    psym = psym(b_top)
                parg = set(psym.list_arguments())
                pshapes = {}
                if self._prefill_data_name in parg:
                    pshapes[self._prefill_data_name] = (bb, b_top)
                if self._prefill_len_name in parg:
                    pshapes[self._prefill_len_name] = (bb,)
                pdtypes = {}
                for src in (arg_params or {}), (aux_params or {}):
                    for k, v in src.items():
                        dt = getattr(v, "dtype", None)
                        if dt is not None:
                            pdtypes.setdefault(k, np.dtype(dt))
                pplan, _rep = plan_memory(psym, pshapes,
                                          dtypes=pdtypes,
                                          sharding=spec)
                if pplan:
                    label = "prefill[b%dxT%d]" % (bb, b_top)
                    r = row(label, pplan)
                    r["peak_bytes"] = pplan["peak_bytes"] + pool
                    programs.append(r)
                    if r["peak_bytes"] > need:
                        need = r["peak_bytes"]
                        offender = label
            mem = {
                "enabled": True,
                "programs": programs,
                "predicted_peak_bytes": need,
                "param_bytes": plan["param_bytes"],
                "pool_bytes": pool,
                "per_slot_bytes": per_slot,
                "offender": offender,
                "sharded": bool(spec),
                "donation": donation,
            }
            # budget is a property of THIS host, not of the plan:
            # digest only the deterministic prediction, or the same
            # program would fingerprint-drift across machines
            mem["digest"] = plan_digest(
                {k: mem[k] for k in ("programs", "predicted_peak_bytes",
                                     "sharded", "donation")})
            budget = device_memory_budget()
            mem["budget_bytes"] = budget
            mem["budget_ok"] = (None if budget is None
                                else need <= budget)
            mem["max_slots_fit"] = (
                max(0, int((budget - (need - pool)) // per_slot))
                if budget is not None and per_slot > 0 else None)
            self.memory_plan = mem
            bad = [(label, d) for label, d in sorted(donation.items())
                   if d is not None and not d["accepted"]]
            if bad:
                detail = "\n".join(
                    "  [%s] %s" % (label, reason)
                    for label, d in bad for reason in d["reasons"])
                msg = ("[memory] DecodeEngine slot-pool donation is "
                       "UNSOUND — an in-place next-state write would "
                       "clobber a state buffer before its last read:"
                       "\n%s" % detail)
                if strict:
                    raise AnalysisError(msg)
                warnings.warn(msg + "\ncontinuing because "
                              "MXNET_ANALYSIS_STRICT=0; the engine "
                              "does NOT donate these buffers safely")
            if mem["budget_ok"] is False:
                fit = mem["max_slots_fit"]
                msg = ("DecodeEngine memory preflight: program %r "
                       "predicts peak %s (slot pool %s for %d slots "
                       "+ params %s) but the device budget is %s — "
                       "the warm set cannot fit%s; shrink num_slots/"
                       "max_len, shard the plan, or raise "
                       "MXNET_MEMORY_BUDGET_BYTES (priced before any "
                       "compile)"
                       % (offender, format_bytes(need),
                          format_bytes(pool), n,
                          format_bytes(plan["param_bytes"]),
                          format_bytes(budget),
                          (" (at most %d slots fit)" % fit
                           if fit is not None else "")))
                if strict:
                    raise AnalysisError("[memory] " + msg)
                warnings.warn(msg)
        except AnalysisError:
            raise
        except Exception as e:      # planner crash must never block
            #                         construction: advisory pass
            warnings.warn("DecodeEngine: memory preflight crashed "
                          "(%r); continuing without a memory plan"
                          % (e,))

    def _check_draft_heads(self, step_sym, draft_sym, state_info,
                           draft_state_info, token_name, pos_name,
                           valid_name):
        """Draft-compatibility contract: the two heads must score the
        SAME vocabulary — acceptance compares the draft's proposal
        against the target's distribution index-for-index, so a vocab
        (or logits-rank) mismatch produces garbage comparisons, not an
        error, and must be refused at construction."""
        def logits_shape(sym_, infos):
            n = self.num_slots
            arg_names = set(sym_.list_arguments())
            shapes = {token_name: (n,)}
            for info in infos:
                shapes[info["name"]] = (n,) + tuple(info["shape"])
            for extra in (pos_name, valid_name):
                if extra in arg_names:
                    shapes[extra] = (n,)
            _a, out, _x = sym_.infer_shape(**shapes)
            return tuple(out[0])
        try:
            t_shape = logits_shape(step_sym, state_info)
            d_shape = logits_shape(draft_sym, draft_state_info)
        except Exception as e:
            warnings.warn("DecodeEngine: cannot infer draft/target "
                          "head shapes (%r); the head-compatibility "
                          "check is skipped" % (e,))
            return
        if t_shape != d_shape:
            raise MXNetError(
                "speculative decode: target head scores %s but the "
                "draft head scores %s — draft and target must share "
                "one vocabulary (and logits layout) for acceptance "
                "to compare them" % (t_shape, d_shape))

    def _optimize_step(self, step_sym, state_info, token_name, pos_name,
                       valid_name, what="step"):
        """Run the kernel-selection optimizer pipeline
        (``analysis.SELECT_OPT_PASSES``) over the step graph under the
        SAME spec the preflight lint uses — slot-pool shapes, slot
        padded axis, state inputs seeded pad-DIRTY — so a selection is
        adopted only via an accepted verdict-gated OptPlan: re-analysis
        no worse, slot-axis row-locality preserved.  Returns
        ``(graph, plan, selection)`` where the graph is what
        StepProgram should compile (the input graph verbatim on
        rejection or crash)."""
        from ..analysis import optimize_graph, SELECT_OPT_PASSES
        try:
            n = self.num_slots
            arg_names = set(step_sym.list_arguments())
            shapes = {token_name: (n,)}
            dtypes = {token_name: np.dtype(np.float32)}
            state_names = []
            for info in state_info:
                shapes[info["name"]] = (n,) + tuple(info["shape"])
                dtypes[info["name"]] = np.dtype(info.get("dtype")
                                                or self._dtype)
                state_names.append(info["name"])
            for extra in (pos_name, valid_name):
                if extra in arg_names:
                    shapes[extra] = (n,)
                    dtypes[extra] = np.dtype(np.float32)
            plan = optimize_graph(
                step_sym, data_shapes=shapes, dtypes=dtypes,
                pad_axes={"slot": {name: 0 for name in shapes}},
                valid_lengths=({"slot": valid_name}
                               if valid_name in arg_names else None),
                pad_dirty=tuple(state_names),
                passes=SELECT_OPT_PASSES)
        except Exception as e:    # optimizer crash must never block
            warnings.warn("DecodeEngine: %s-graph optimization "
                          "crashed (%r); serving the unmodified graph"
                          % (what, e))
            return step_sym, None, None
        if plan.accepted and plan.symbol is not None and plan.rewrites:
            # the fingerprint-visible selection summary: which fused
            # kernels the accepted plan swapped in, and where
            selection = [{"op": "_cache_write_row",
                          "site": a.node}
                         for a in plan.actions
                         if a.kind == "select"]
            return plan.symbol, plan, selection
        if not plan.accepted:
            warnings.warn("DecodeEngine: %s-graph optimization "
                          "rejected (%s); serving the unmodified graph"
                          % (what, plan.reason))
        return step_sym, plan, None

    # ---------------------------------------------------------- lifecycle
    def start(self):
        if self._adm.closed:
            raise EngineClosedError(
                "engine is closed; build a new DecodeEngine")
        if self._worker is None:
            self._worker = threading.Thread(target=self._run,
                                            name="mxnet-decode-worker",
                                            daemon=True)
            self._worker.start()
        self._ensure_replica_threads()
        return self

    def _ensure_replica_threads(self):
        """Spawn the per-replica scheduler threads (multi-replica only:
        the single-replica worker steps its pool inline)."""
        if not self._multi:
            return
        for rep in self._replicas:
            if rep.thread is None:
                rep.thread = threading.Thread(
                    target=self._decode_replica_run, args=(rep,),
                    name="mxnet-decode-replica-%d" % rep.index,
                    daemon=True)
                rep.thread.start()

    def close(self, drain=True):
        """Stop admitting.  With ``drain``, queued AND slot-resident
        requests run to completion first; otherwise queued futures
        fail with EngineClosedError and in-flight requests resolve
        with their PARTIAL tokens (finish_reason "closed")."""
        # regulator first: a drain must not race a still-ticking
        # regulator shedding the queued work it is trying to finish
        if self._regulator is not None:
            self._regulator.close()
            self._regulator = None
        if self._sup_owner:
            from . import supervisor as _supervisor
            self._sup_owner = False
            _supervisor.engine_release(self)
        if not drain:
            self._abort = True
        self._adm.close(drain=drain)
        if self._worker is not None:
            self._worker.join(timeout=None if drain else 60)
            if not self._worker.is_alive():
                self._worker = None
        elif drain:
            # never started: route the backlog on the caller's thread
            # (replica threads must exist to drain the routed half)
            self._ensure_replica_threads()
            self._run()
        if self._multi:
            # router is done; replica threads finish seated generations
            # (drain) or abort with partial output, then exit
            with self._dr_lock:
                self._dr_stop = True
                self._dr_cond.notify_all()
            for rep in self._replicas:
                if rep.thread is not None:
                    rep.thread.join(timeout=None if drain else 60)
                    if not rep.thread.is_alive():
                        rep.thread = None
        if self._eff is not None:
            self._eff.close()
            self._eff = None
        # the timeline ring is process-wide (no per-engine state to
        # reclaim); drop the reference so a closed engine cannot feed
        self._tl = None
        if self._tm is not None:
            self._tm.close()
        if self._obs_name is not None:
            _telemetry.recorder.unregister_heartbeat(self._obs_name)
            _telemetry.recorder.unregister_engine(self._obs_name)
            self._obs_name = None
        if self._alert_owner is not None:
            _telemetry.default_manager().remove_owner(self._alert_owner)
            self._alert_owner = None
        if self._owns_recorder:
            token, self._owns_recorder = self._owns_recorder, False
            _telemetry.recorder.recorder_release(token)
        if self._owns_http_server:
            self._owns_http_server = False
            _telemetry.server.engine_release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------- client
    def submit(self, prompt, max_new_tokens=None, deadline_ms=None,
               on_token=None, request_id=None, tenant=None):
        """Enqueue one generation request; returns a Future resolving
        to a :class:`DecodeResult`.

        ``prompt`` is a non-empty sequence of token ids; generation
        continues until ``eos_id`` is sampled, ``max_new_tokens`` are
        out, the slot's ``max_len`` positions fill, or the deadline
        passes (partial result, ``expired=True``).

        ``on_token`` optionally streams the generation: it is called
        with each generated token id (int) in order — the exact prefix
        the final ``DecodeResult.tokens`` will hold — from the engine's
        scheduler thread, so it must be cheap and thread-safe.  A
        raising callback evicts only its own request: the future fails
        with the callback's exception and co-resident requests keep
        generating.

        ``request_id`` additionally publishes the stream over HTTP:
        each generated token becomes a ``decode.token`` event on the
        ``GET /events`` SSE endpoint (``{"request_id", "index",
        "token"}``, with a final ``{"request_id", "done": true,
        "finish_reason"}`` frame), so any SSE client can follow one
        request's generation by filtering on its id — and resume after
        a disconnect via the standard ``Last-Event-ID`` replay the
        EventHub already implements.  Requires telemetry; None (the
        default) publishes nothing.

        ``tenant`` optionally attributes this request to an accounting
        tenant: the serving-efficiency plane (telemetry/goodput.py)
        then tracks its useful FLOPs, generated tokens, end-to-end
        latency, and outcome under a bounded-cardinality ``tenant``
        label (``MXNET_TELEMETRY_TENANTS_MAX`` distinct labels; later
        tenants aggregate into ``"other"``).  Pure observability —
        scheduling is tenant-blind."""
        if self._adm.closed:
            raise EngineClosedError("decode engine is closed")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("decode needs a non-empty prompt (feed at "
                             "least a BOS token)")
        if len(prompt) >= self.max_len:
            raise MXNetError(
                "prompt length %d leaves no room to generate within "
                "max_len=%d positions" % (len(prompt), self.max_len))
        cap = self.max_len - len(prompt)
        if max_new_tokens is None:
            max_new_tokens = cap
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        max_new_tokens = min(max_new_tokens, cap)
        if deadline_ms is None and self._default_deadline_s > 0:
            deadline_ms = self._default_deadline_s * 1e3
        deadline = None if not deadline_ms else \
            time.monotonic() + float(deadline_ms) / 1e3
        fut = Future()
        trace = None
        if self._tm is not None:
            self._tm.requests.inc()
            if self._trace_chain is not None:
                trace = _telemetry.LazyTrace(self._trace_chain,
                                             name="decode.request")
        req = DecodeRequest(prompt, max_new_tokens, fut,
                            deadline=deadline, trace=trace,
                            on_token=on_token,
                            sse_id=(str(request_id)
                                    if request_id is not None
                                    and self._tm is not None else None))
        if req.sse_id is not None:
            # terminal stream frame on ANY outcome — the future is the
            # one place every finish/failure/cancel path converges
            fut.add_done_callback(
                lambda f, _req=req: self._emit_done(_req, f))
        if tenant is not None and self._eff is not None:
            # tenant accounting (goodput.py): resolve the label ONCE
            # under the cardinality guard; outcome/latency/tokens ride
            # the same every-outcome convergence point as the SSE frame
            req.tenant = self._eff.tenant_enter(tenant)
            if req.tenant is not None:
                fut.add_done_callback(
                    lambda f, _eff=self._eff, _t=req.tenant,
                    _t0=req.t_enqueue: _eff.tenant_done(_t, f, _t0))
        # padded-element cost for the regulator's cost-aware shed: a
        # decode request prices as its bucketed prompt plus the
        # positions its generation budget can occupy.  Under
        # speculative decode every generated token costs up to k+1
        # TARGET positions (the verify window scores the whole draft
        # whatever gets accepted), so the width multiplies the
        # generation half — the regulator's cost ordering and the
        # admission-time padded-element accounting stay honest.
        req.cost = int(_next_pow2(len(prompt))
                       + max_new_tokens * (self._spec_k + 1))
        # a deadline hit — queued or mid-generation — COMPLETES the
        # request with whatever was generated (admission._deliver
        # routes DeadlineExceededError through this instead of failing)
        req.on_expire = lambda exc, r=req: DecodeResult(
            r.tokens, "deadline", n_steps=r.n_steps,
            prompt_len=len(r.prompt))
        try:
            self._adm.admit(req)
        except Exception as e:
            if trace is not None:
                trace.abort(type(e).__name__)
            raise
        return fut

    def generate(self, prompt, max_new_tokens=None, deadline_ms=None,
                 timeout=None):
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout=timeout)

    # ------------------------------------------------------------- worker
    def _occupied_count(self):
        return sum(r.occupied_count() for r in self._replicas)

    def _heartbeat(self):
        """Watchdog probe: progress age of the scheduler loop, busy
        when any slot is generating or work is queued.  A step program
        wedged in dispatch (donated-buffer failure modes, a hung
        backend) shows up as busy + growing age — named by this
        heartbeat, not inferred from throughput silence.  Multi-replica
        engines report the STALEST busy replica (one wedged pool must
        trip the watchdog even while its siblings keep generating)
        plus a per-replica breakdown the flight bundle captures."""
        now = time.monotonic()
        queued = len(self._adm)
        occupied = self._occupied_count()
        out = {"age_s": now - self._hb_t,
               "busy": bool(self._hb_busy or queued or occupied),
               "in_step": bool(self._hb_busy),
               "queued": queued, "slots_occupied": occupied,
               "kind": "decode",
               "engine": (self._tm.engine_label
                          if self._tm is not None else None)}
        if self._multi:
            ages = [now - self._hb_t] if (self._hb_busy or queued) else []
            reps = []
            for r in self._replicas:
                age = now - r.hb_t
                if r.healthy and (r.occupied_count() or r.pending):
                    ages.append(age)
                reps.append({"replica": r.label, "healthy": r.healthy,
                             "slots_occupied": r.occupied_count(),
                             "pending": len(r.pending),
                             "age_s": round(age, 3)})
            out["replicas"] = reps
            out["busy"] = bool(ages)
            out["age_s"] = max(ages) if ages else now - self._hb_t
            out["in_step"] = any(r.in_step for r in self._replicas)
        return out

    def _run(self):
        if self._multi:
            self._router_run()
        else:
            self._single_run(self._replicas[0])

    def _single_run(self, rep):
        """The single-replica fast path: one thread admits, seats, and
        steps the one slot pool — exactly the pre-replica engine."""
        while True:
            self._hb_t = rep.hb_t = time.monotonic()
            self._hb_busy = False
            try:
                if self._abort:
                    for i in rep.occupied():
                        self._finish_slot(rep, i, "closed")
                    return
                occ = rep.occupied()
                free = self.num_slots - len(occ)
                if not occ:
                    batch = self._adm.take(free, 0.0)
                    if batch is None:
                        return          # closed and drained
                    self._join_many(rep, batch)
                    continue
                # busy: admit opportunistically (never block a step),
                # and keep queued deadlines honest even when no slot
                # is free — expiry must not wait for a drain
                if free:
                    polled = self._adm.poll(free)
                    if polled:
                        self._join_many(rep, polled)
                else:
                    self._adm.sweep()
                self._hb_busy = True    # a wedged step must read busy
                self._step_once(rep)
            except Exception as e:      # fail the batch, keep serving
                for i in rep.occupied():
                    req = rep.slots[i]
                    rep.slots[i] = None
                    rep.valid_np[i] = 0.0
                    if not req.future.done():
                        _fail_future(req.future, e)
                    if req.trace is not None:
                        req.trace.abort(type(e).__name__)
                # a failed step dispatch may have consumed the DONATED
                # state buffers (non-CPU backends): rep.states would
                # point at deleted arrays and wedge every later step —
                # the pool is empty now, so fresh zeros lose nothing
                rep.states = rep.program.init_states()
                rep.tokens_np.fill(0.0)
                rep.pos_np.fill(0.0)
                rep.reset_np.fill(0.0)
                rep.spec_np.fill(0.0)

    # ------------------------------------------------------------- router
    def _router_run(self):
        """Multi-replica scheduler front end: takes admitted requests
        and routes each to the healthy replica with the most free
        slots, where it PINS (per-slot state is device-resident).  The
        router never promises more than the fleet's free capacity, so
        backlog waits in admission where deadlines sweep and
        backpressure applies."""
        while True:
            self._hb_t = time.monotonic()
            self._hb_busy = False
            try:
                if self._abort:
                    with self._dr_cond:
                        self._dr_cond.notify_all()
                    return
                with self._dr_lock:
                    live = [r for r in self._replicas if r.healthy]
                    free_total = sum(max(0, r.assignable())
                                     for r in live)
                if not live:
                    # dead fleet: fail incoming work fast instead of
                    # wedging the queue (the flight recorder already
                    # dumped on each replica's retirement)
                    batch = self._adm.take(self.num_slots, 0.0)
                    if batch is None:
                        return
                    err = MXNetError(
                        "all %d decode replicas are unhealthy (step "
                        "failures drained them); build a new engine"
                        % len(self._replicas))
                    for req in batch:
                        _fail_future(req.future, err)
                        if req.trace is not None:
                            req.trace.abort("MXNetError")
                    continue
                if free_total <= 0:
                    # pool full: keep queued deadlines honest while
                    # waiting for a leave to free capacity
                    self._adm.sweep()
                    if self._adm.closed and not len(self._adm):
                        return
                    self._slot_free.wait(0.05)
                    self._slot_free.clear()
                    continue
                batch = self._adm.take(free_total, 0.0)
                if batch is None:
                    return              # closed and drained
                self._hb_busy = True
                for req in batch:
                    # per-request isolation: a failing assign (or its
                    # telemetry) must fail THAT request's future, not
                    # silently drop the rest of the popped batch
                    try:
                        self._assign(req)
                    except Exception as e:
                        if not req.future.done():
                            _fail_future(req.future, e)
                            if req.trace is not None:
                                req.trace.abort(type(e).__name__)
            except Exception:           # defense: never lose the router
                continue

    def _assign(self, req):
        """Route one admitted request to the freest healthy replica.
        The append happens under the same lock the replica threads'
        exit checks hold, and only onto an ``accepting`` replica — a
        request must never land on a queue no thread will drain."""
        with self._dr_lock:
            live = [r for r in self._replicas
                    if r.healthy and r.accepting]
            if live:
                r = max(live, key=lambda x: (x.assignable(), -x.index))
                r.pending.append(req)
                self._dr_cond.notify_all()
                return
            unhealthy = any(not r.healthy for r in self._replicas)
        err = (MXNetError("all %d decode replicas are unhealthy"
                          % len(self._replicas)) if unhealthy
               else EngineClosedError("engine closed before seating"))
        _fail_future(req.future, err)
        if req.trace is not None:
            req.trace.abort(type(err).__name__)

    def _decode_replica_run(self, rep):
        """One replica's scheduler loop: seat routed requests, step the
        pool, deliver leaves.  A step dispatch that raises retires the
        replica — seated requests are evicted with their PARTIAL output
        (finish_reason "error"), routed-but-unseated ones re-route, and
        co-resident replicas keep generating untouched."""
        while True:
            rep.hb_t = time.monotonic()
            if self._abort:
                with self._dr_lock:
                    rep.accepting = False
                    pend = list(rep.pending)
                    rep.pending.clear()
                e = EngineClosedError("engine closed before seating")
                for req in pend:
                    if not req.future.done():
                        _fail_future(req.future, e)
                        if req.trace is not None:
                            req.trace.abort(type(e).__name__)
                for i in rep.occupied():
                    self._finish_slot(rep, i, "closed")
                return
            self._sweep_pending(rep, time.monotonic())
            seats = []
            stolen = 0
            with self._dr_lock:
                n_free = rep.free_slots()
                while rep.pending and len(seats) < n_free:
                    seats.append(rep.pending.popleft())
                if len(seats) < n_free and rep.healthy:
                    # cross-replica work stealing (ROADMAP a3): a
                    # request routed to a sibling whose pool is FULL
                    # would otherwise wait a whole generation for its
                    # pinned replica — re-offer it here instead (it
                    # has not seated, so no device state moves).  The
                    # window exists after a failure re-route overflows
                    # a sibling, or when a pool saturates between the
                    # router's capacity check and the seat.
                    for sib in self._replicas:
                        if sib is rep or len(seats) >= n_free:
                            continue
                        while sib.pending and sib.free_slots() == 0 \
                                and len(seats) < n_free:
                            seats.append(sib.pending.popleft())
                            stolen += 1
            if stolen:
                with self._lock:
                    self._steals += stolen
                if self._tm is not None:
                    self._tm.steals.inc(stolen)
                if self._tl is not None:
                    self._tl.instant("decode.steal", "decode",
                                     "decode:%s" % rep.label,
                                     args={"stolen": stolen})
            live = []
            for req in seats:
                # honor deadlines that expired in the routed-but-
                # unseated window exactly like the admission sweep
                # (AdmissionController.expire_request): the request
                # completes with its (empty) partial output
                if req.expired():
                    self._adm.expire_request(req,
                                             "expired before seating")
                else:
                    live.append(req)
            if live:
                self._join_many(rep, live)
            if not rep.occupied_count():
                with self._dr_cond:
                    if rep.pending:
                        continue
                    if self._dr_stop or not rep.healthy:
                        # refuse further routing ATOMICALLY with the
                        # exit decision — the router must never hand
                        # a request to a dead scheduler thread
                        rep.accepting = False
                        return
                    self._dr_cond.wait(0.05)
                continue
            rep.in_step = True
            try:
                self._step_once(rep)
            except Exception as e:
                rep.in_step = False
                self._decode_replica_failed(rep, e)
                return
            rep.in_step = False
            rep.hb_t = time.monotonic()
            if rep.free_slots():
                self._slot_free.set()

    def _sweep_pending(self, rep, now):
        """Per-iteration deadline sweep over this replica's routed-but-
        unseated queue — the one waiting room the admission sweep can
        no longer see.  Matters after a sibling replica's failure
        re-routes more requests than this replica has free slots: the
        overflow must not wait a whole generation to expire."""
        if not rep.pending:
            return
        expired = []
        with self._dr_lock:
            if any(r.deadline is not None and now >= r.deadline
                   for r in rep.pending):
                keep = collections.deque()
                for r in rep.pending:
                    if r.deadline is not None and now >= r.deadline:
                        expired.append(r)
                    else:
                        keep.append(r)
                rep.pending = keep
        for r in expired:
            self._adm.expire_request(r, "expired before seating")

    def _decode_replica_failed(self, rep, exc):
        """Retire one replica after a failed step dispatch: seated
        requests are evicted with their PARTIAL tokens (finish_reason
        "error" — the donated state buffers may be consumed, so the
        pool cannot step again), routed requests re-route, and the
        flight recorder dumps while the evidence is fresh."""
        with self._dr_lock:
            rep.healthy = False
            rep.accepting = False
            orphans = list(rep.pending)
            rep.pending.clear()
            stopping = self._dr_stop
            self._dr_cond.notify_all()
        warnings.warn(
            "decode replica %d (%s) retired after a step failure (%r): "
            "%d seated request(s) evicted with partial output, traffic "
            "re-routed to %d sibling(s)"
            % (rep.index, rep.ctx if rep.ctx is not None else "cpu(0)",
               exc, rep.occupied_count(),
               sum(1 for x in self._replicas if x.healthy)))
        for i in rep.occupied():
            self._finish_slot(rep, i, "error")
        if rep.tm_failures is not None:
            rep.tm_failures.inc()
        if self._tl is not None:
            self._tl.instant("decode.replica_failed", "decode",
                             "decode:%s" % rep.label,
                             args={"error": repr(exc)})
        fr = _telemetry.recorder.flight_recorder()
        if fr is not None:
            fr.dump("replica_failed:%s:%s"
                    % (self._obs_name or "decode", rep.label),
                    detail={"replica": rep.describe(),
                            "error": repr(exc)})
        for req in orphans:
            if stopping:
                # sibling scheduler threads may already have drained
                # and exited — a re-assigned request would never seat
                # and its future would hang forever; fail it instead
                if not req.future.done():
                    _fail_future(req.future, exc)
                    if req.trace is not None:
                        req.trace.abort(type(exc).__name__)
            else:
                self._assign(req)
        self._slot_free.set()

    def rehabilitate(self, replicas=None):
        """Replica probation/re-warm (ROADMAP follow-up a2): rebuild
        every retired replica's programs from scratch (its donated
        state buffers may be consumed), re-warm them — drawn from the
        persistent AOT cache when one is configured, so re-entry
        compiles nothing — and admit the replica back only after ONE
        probe step matches a healthy sibling's output bitwise
        (``StepProgram.probe_step``: fixed key, fixed tick, zero
        scratch state — deterministic for stochastic samplers too).
        A replica that fails any stage stays retired.

        ``replicas`` restricts probation to those replica indices
        (the supervisor's one-due-replica-at-a-time calls; None =
        every unhealthy replica).

        Returns one outcome dict per attempted replica:
        ``{"replica", "ok", "reason"}``.
        """
        if self._adm.closed:
            raise EngineClosedError("decode engine is closed")
        want = None if replicas is None else {int(i) for i in replicas}
        return [self._rehabilitate_one(r) for r in self._replicas
                if not r.healthy and (want is None or r.index in want)]

    def _rehabilitate_one(self, rep):
        out = {"replica": rep.label, "ok": False, "reason": None}
        with self._dr_lock:
            sib = next((x for x in self._replicas
                        if x.healthy and x is not rep), None)
        if sib is None:
            out["reason"] = ("no healthy sibling to probe against; "
                             "build a new engine")
            return out
        try:
            fresh = self._new_replica(rep.index, rep.ctx, rep.plan)
            # probation warmup: exactly engine.warmup's per-replica
            # sequence (step twice for committed-sharding parity,
            # row-write kernels, prefill buckets) — with an AOT cache
            # every one of these loads instead of tracing
            self._warm_replica(fresh)
            # the probation gate: one probe step, bitwise against the
            # live sibling's program, before any traffic
            want = sib.program.probe_step()
            got = fresh.program.probe_step()
            if not (len(want) == len(got)
                    and all(np.array_equal(a, b, equal_nan=True)
                            for a, b in zip(want, got))):
                out["reason"] = ("probe step diverged bitwise from "
                                 "healthy replica %s" % sib.label)
                return out
        except Exception as e:
            out["reason"] = repr(e)
            return out
        with self._dr_lock:
            rep.program = fresh.program
            rep.prefill_caches = fresh.prefill_caches
            rep.prefill_buckets = fresh.prefill_buckets
            rep.slots = list(fresh.slots)
            rep.tokens_np = fresh.tokens_np
            rep.pos_np = fresh.pos_np
            rep.valid_np = fresh.valid_np
            rep.reset_np = fresh.reset_np
            rep.spec_np = fresh.spec_np
            rep.states = fresh.states
            rep.pending.clear()
            rep.in_step = False
            rep.healthy = True
            rep.accepting = True
            rep.thread = None
            rep.probations += 1
            rep.hb_t = time.monotonic()
            self._dr_cond.notify_all()
        self._ensure_replica_threads()
        self._slot_free.set()
        warnings.warn(
            "decode replica %d (%s) rehabilitated after probation: "
            "probe step bitwise-equal to replica %s"
            % (rep.index, rep.ctx if rep.ctx is not None else "cpu(0)",
               sib.label))
        out["ok"] = True
        return out

    def _join(self, rep, req):
        """Seat one admitted request BETWEEN steps (single-request
        compatibility wrapper over :meth:`_join_many`)."""
        self._join_many(rep, [req])

    def _join_many(self, rep, reqs):
        """Seat a batch of admitted requests in free slots BETWEEN
        steps: zero (or prefill-fill) each slot's state rows, stage
        first tokens, mark slots valid.  No shape changes anywhere —
        the next step dispatch reuses the same compiled program.

        With a prefill graph and ``MXNET_DECODE_COALESCE_PREFILL``
        (default on), joiners landing in the same iteration COALESCE:
        one dispatch per pow2 (batch, prompt) bucket instead of batch 1
        per joiner — at concurrency the TTFT cost of the Nth joiner
        stops being N serial prefill dispatches (ROADMAP 4b; the
        ``decode_bench --prefill`` sweep measures the win).  Serial
        mode (knob off) dispatches per request, byte-for-byte the
        pre-coalescing engine."""
        seated = [req for req in reqs if self._seat_slot(rep, req)]
        if not seated:
            return
        if rep.prefill_caches:
            # serial mode is the degenerate grouping — one singleton
            # group per joiner dispatches the identical (1, bucket)
            # program the pre-coalescing engine did, through the SAME
            # code path (no serial/coalesced divergence to maintain)
            groups = []                 # [(bucket, [reqs])], seat order
            for req in seated:
                b = next(bk for bk in rep.prefill_buckets
                         if bk >= len(req.prompt))
                g = next((g for g in groups if g[0] == b),
                         None) if self._coalesce else None
                if g is None:
                    groups.append((b, [req]))
                else:
                    g[1].append(req)
            for b, grp in groups:
                self._prefill_group(rep, b, grp)
        else:
            for req in seated:
                # the previous occupant's state rows are cleared IN
                # the next step dispatch (StepProgram reset mask) — a
                # join costs zero device traffic of its own
                slot = req.slot
                rep.reset_np[slot] = 1.0
                rep.tokens_np[slot] = req.prompt[0]
                rep.pos_np[slot] = 0.0
                req.prompt_i = 1
                # spec eligibility starts with the FIRST sampling step
                # — the one that consumes the last prompt token
                rep.spec_np[slot] = (1.0 if req.prompt_i
                                     >= len(req.prompt) else 0.0)
        for req in seated:
            if req.slot is not None and rep.slots[req.slot] is req:
                self._check_finish(rep, req.slot)

    def _seat_slot(self, rep, req):
        """Claim a free slot for one admitted request; False when the
        request was cancelled before seating (counted as a leave so the
        scraped series and stats() carry the same numbers)."""
        if not req.future.set_running_or_notify_cancel():
            if req.trace is not None:
                req.trace.abort("cancelled")
            with self._lock:
                self._leaves += 1
            if self._tm is not None:
                self._tm.leave("cancelled")
            return False
        slot = rep.slots.index(None)
        req.slot = slot
        req.t_join = time.perf_counter()
        rep.slots[slot] = req
        rep.valid_np[slot] = 1.0
        rep.spec_np[slot] = 0.0
        with self._lock:
            self._joins += 1
        if self._tm is not None:
            self._tm.joins.inc()
        if self._tl is not None:
            self._tl.instant("decode.join", "decode",
                             "decode:%s" % rep.label,
                             args={"slot": slot,
                                   "request": req.sse_id,
                                   "prompt_len": len(req.prompt)})
        return True

    def _fail_seated(self, rep, req, exc):
        """Fail ONE seated request and free its slot — the per-request
        isolation every prefill/callback failure path rides: co-
        resident mid-generation requests share no state with it and
        keep their partial generations."""
        slot = req.slot
        if slot is not None and rep.slots[slot] is req:
            rep.slots[slot] = None
            rep.valid_np[slot] = 0.0
            rep.spec_np[slot] = 0.0
        with self._lock:
            self._leaves += 1
        if self._tm is not None:
            self._tm.leave("error")
        if req.tenant is not None and req.uflops \
                and self._eff is not None:
            self._eff.tenant_useful(req.tenant, req.uflops)
            req.uflops = 0
        _fail_future(req.future, exc)
        if req.trace is not None:
            req.trace.abort(type(exc).__name__)

    def _prefill_group(self, rep, bucket, group):
        """The coalesced path: every joiner whose prompt pads into
        ``bucket`` rides ONE dispatch at the next pow2 batch extent
        (dead rows padded with zero prompts and length 0 — exactly the
        all-pad rows warmup feeds), output state rows scattered into
        each request's slot.  A failed dispatch fails the GROUP's
        requests (they share that one program invocation) and nothing
        else; the chaos seam still trips per request so a fault plan
        targeting one joiner fails exactly one."""
        live = []
        for req in group:
            if _faults.ACTIVE:
                try:
                    _faults.trip("decode.prefill", replica=rep.label)
                except Exception as e:
                    self._fail_seated(rep, req, e)
                    continue
            live.append(req)
        if not live:
            return
        bb = next(b for b in self._prefill_batches if b >= len(live))
        arr = np.zeros((bb, bucket), np.float32)
        lens = np.zeros((bb,), np.float32)
        for r_i, req in enumerate(live):
            plen = len(req.prompt)
            arr[r_i, :plen] = req.prompt
            lens[r_i] = plen
        t_pf0 = time.perf_counter()
        try:
            outs = rep.prefill_caches[bucket].run({
                self._prefill_data_name: arr,
                self._prefill_len_name: lens})
            with self._lock:
                self._prefill_dispatches += 1
            if self._sampler.greedy:
                first = np.asarray(outs[0])
            else:
                first = rep.program.sample_tokens(outs[0])
            rows_all = [np.asarray(o) for o in outs[1:]]
        except Exception as e:
            for req in live:
                self._fail_seated(rep, req, e)
            return
        # element split + FLOPs ledger for this one dispatch: the
        # program computed bb*bucket positions; Σ prompt lengths of
        # them carried real tokens, the rest were batch-row padding
        # and sequence overhang
        live_elems = int(sum(len(r.prompt) for r in live))
        padded_elems = bb * bucket
        if self._tm is not None:
            self._tm.prefill_elems(bucket, live_elems,
                                   padded_elems - live_elems)
        if self._tl is not None:
            self._tl.complete("decode.prefill", "decode",
                              "decode:%s" % rep.label, t_pf0,
                              time.perf_counter(),
                              args={"bucket": bucket, "group": len(live)})
        if self._eff is not None:
            shape_key = tuple(sorted(
                (k, v.shape)
                for k, v in ((self._prefill_data_name, arr),
                             (self._prefill_len_name, lens))))
            useful = self._eff.record_batch(
                rep.label, rep.prefill_caches[bucket].flops_for(
                    shape_key), live_elems, padded_elems)
            if useful:
                for req in live:
                    if req.tenant is not None:
                        req.uflops += (useful * len(req.prompt)
                                       // live_elems)
        for r_i, req in enumerate(live):
            rows = {name: rows_all[i][r_i]
                    for i, name in enumerate(rep.program.state_names)}
            self._commit_prefill(rep, req, rows, first[r_i])

    def _commit_prefill(self, rep, req, rows, first):
        """Scatter one request's prefill output rows into its slot and
        deliver the first generated token (row scatter stays one
        traced-index kernel per state shape — never a new compile)."""
        slot = req.slot
        rep.states = rep.program.write_row(rep.states, slot, rows)
        if self._spec_k:
            # the prefill graph produced TARGET rows only; the draft
            # never saw this prompt, and the previous occupant's draft
            # rows must not leak into its proposals — start it cold.
            # (Draft quality only moves the accept RATE; acceptance
            # keeps the emitted stream exact regardless.)
            rep.states = rep.program.zero_row(rep.states, slot,
                                              which="draft")
            rep.spec_np[slot] = 1.0
        rep.reset_np[slot] = 0.0        # prefill rows are live data
        req.prompt_i = len(req.prompt)
        req.tokens.append(int(first))
        now = time.monotonic()
        req.t_first_tok = req.t_last_tok = now
        rep.tokens_np[slot] = first
        rep.pos_np[slot] = float(len(req.prompt))
        with self._lock:
            self._tokens_out += 1
        if self._tm is not None:
            self._tm.tokens.inc()
            self._tm.ttft.observe(now - req.t_enqueue)
        self._emit_token(req, first)
        if req.on_token is not None:
            self._fire_on_token(rep, req, int(first))

    def _emit_token(self, req, tok):
        """Publish one generated token onto the /events EventHub as a
        ``decode.token`` event keyed by the request's client-supplied
        id — the SSE half of per-token streaming (ROADMAP 4a residual).
        Requests without a ``request_id`` pay a single attribute check."""
        if req.sse_id is None:
            return
        if self._tl is not None:
            # streaming requests already pay an SSE publish per token;
            # the ring append is cheaper and gives request_autopsy the
            # exact per-token gaps instead of step-derived estimates
            self._tl.instant("decode.token", "decode", "decode.tokens",
                             args={"request": req.sse_id,
                                   "index": len(req.tokens) - 1})
        try:
            _telemetry.server.publish_event(
                "decode.token",
                {"request_id": req.sse_id,
                 "engine": (self._tm.engine_label
                            if self._tm is not None else None),
                 "index": len(req.tokens) - 1, "token": int(tok)})
        except Exception:
            pass    # the stream is observability: never fail a request

    def _emit_done(self, req, fut):
        """Terminal SSE frame, fired from the request future's done
        callback — hooking the future (not the individual finish
        paths) means EVERY terminal outcome publishes exactly one
        ``{"done": true}`` frame: normal finishes, deadline partials,
        replica failures, a raising on_token callback, engine close,
        and client-side cancellation alike.  An SSE consumer can
        therefore treat stream silence as in-flight, never as an
        ambiguous death."""
        if fut.cancelled():
            reason = "cancelled"
        elif fut.exception() is not None:
            reason = "error"
        else:
            reason = getattr(fut.result(), "finish_reason", "eos")
        try:
            _telemetry.server.publish_event(
                "decode.token",
                {"request_id": req.sse_id,
                 "engine": (self._tm.engine_label
                            if self._tm is not None else None),
                 "done": True, "finish_reason": reason,
                 "tokens": len(req.tokens)})
        except Exception:
            pass

    def _fire_on_token(self, rep, req, tok):
        """Streaming hook: a raising callback evicts ONLY its own
        request (future fails with the exception, slot frees, co-
        residents untouched).  Returns False when the request was
        evicted."""
        try:
            req.on_token(int(tok))
            return True
        except Exception as e:
            self._fail_seated(rep, req, e)
            return False

    def _step_once(self, rep):
        t0 = time.perf_counter()
        now = time.monotonic()
        # per-iteration deadline check folded into ONE slot scan: an
        # expired slot-resident request completes with its partial
        # tokens and frees the slot for queued work — mid-generation
        # eviction, not failure
        occ = []
        for i, req in enumerate(rep.slots):
            if req is None:
                continue
            if req.deadline is not None and now >= req.deadline:
                self._finish_slot(rep, i, "deadline")
            else:
                occ.append(i)
        if not occ:
            return
        if _faults.ACTIVE:
            # chaos seam: a raise retires this replica through the
            # real step-failure path (partial-output eviction +
            # re-route); a hang wedges the pool for the watchdog
            _faults.trip("decode.step", replica=rep.label)
        if self._spec_k:
            toks_mat, counts, rep.states = rep.program.step_spec(
                rep.tokens_np, rep.pos_np, rep.valid_np, rep.spec_np,
                rep.states, reset=rep.reset_np)
            rep.reset_np.fill(0.0)
            if self._eff is not None:
                # FLOPs ledger, BEFORE the slot advance (a slot that
                # finishes this very step must still absorb its tenant
                # share): committed positions = Σ counts over occupied
                # slots (spec mask 0 rows commit exactly 1), the rest
                # of the K-position verify window was rejected drafts
                cl = counts.tolist()
                committed = int(sum(cl[i] for i in occ))
                self._ledger_step(
                    rep, occ,
                    self._eff.record_spec_step(
                        rep.label,
                        _goodput.price_step_program(rep.program),
                        len(occ), self.num_slots, committed,
                        self._spec_k + 1))
            new_tokens = self._advance_spec(rep, occ, toks_mat, counts)
        else:
            sampled, rep.states = rep.program.step(
                rep.tokens_np, rep.pos_np, rep.valid_np, rep.states,
                reset=rep.reset_np)
            rep.reset_np.fill(0.0)      # consumed: rows are zeroed now
            if self._eff is not None:
                self._ledger_step(
                    rep, occ,
                    self._eff.record_step(
                        rep.label,
                        _goodput.price_step_program(rep.program),
                        len(occ), self.num_slots))
            # one C-level conversion instead of num_slots
            # ndarray-scalar __getitem__ calls: the slot loop below is
            # the scheduler's per-step GIL cost, and with replica
            # routing two of these loops interleave on the host —
            # every microsecond here is paid per step per replica
            sampled_l = sampled.tolist()
            new_tokens = 0
            t_tok = time.monotonic()    # one stamp serves every slot
            for i in occ:
                req = rep.slots[i]
                req.n_steps += 1
                rep.pos_np[i] += 1.0
                if req.prompt_i < len(req.prompt):
                    # teacher forcing: the sample is discarded, the
                    # next prompt token rides the next step
                    rep.tokens_np[i] = req.prompt[req.prompt_i]
                    req.prompt_i += 1
                else:
                    tok = sampled_l[i]
                    req.tokens.append(int(tok))
                    rep.tokens_np[i] = tok
                    new_tokens += 1
                    if req.t_first_tok is None:
                        req.t_first_tok = t_tok
                        if self._tm is not None:
                            self._tm.ttft.observe(t_tok
                                                  - req.t_enqueue)
                    req.t_last_tok = t_tok
                    self._emit_token(req, tok)
                    if req.on_token is not None \
                            and not self._fire_on_token(rep, req, tok):
                        continue    # evicted by its own callback
                self._check_finish(rep, i)
        t1 = time.perf_counter()
        dt_ms = (t1 - t0) * 1e3
        with self._lock:
            self._steps += 1
            self._tokens_out += new_tokens
            self._step_ms.append(dt_ms)
        if self._tl is not None:
            self._tl.complete("decode.step", "decode",
                              "decode:%s" % rep.label, t0, t1,
                              args={"live": len(occ),
                                    "tokens": new_tokens})
        if self._tm is not None:
            self._tm.steps.inc()
            if new_tokens:
                self._tm.tokens.inc(new_tokens)
            rep.tm_step_ms.observe(dt_ms)
            # slot-occupancy split of this dispatch (ISSUE 18
            # satellite): the persistent step computed num_slots rows
            # whatever the occupancy — scraped, not inferred
            self._tm.slot_steps_live.inc(len(occ))
            dead = self.num_slots - len(occ)
            if dead:
                self._tm.slot_steps_dead.inc(dead)

    def _ledger_step(self, rep, occ, useful):
        """Spread one step dispatch's useful FLOPs over the live slots
        for tenant accounting (integer shares; the remainder stays in
        the engine-level ledger, which is exact by construction)."""
        if not useful:
            return
        share = useful // len(occ)
        if not share:
            return
        for i in occ:
            req = rep.slots[i]
            if req.tenant is not None:
                req.uflops += share

    def _advance_spec(self, rep, occ, toks_mat, counts):
        """The variable-width slot advance (ISSUE 15): slot ``i``
        committed ``counts[i]`` positions this dispatch and
        ``toks_mat[i, :counts[i]]`` holds its accepted tokens in
        generation order — the exact ``greedy_decode`` prefix under
        the greedy sampler.  Emission truncates at eos / max_new /
        max_len (a truncated slot always FINISHES, so positions the
        program committed past the truncation point free with the
        slot); ``on_token`` and the SSE stream fire once per accepted
        token, in order, exactly like the single-token loop."""
        toks_l = toks_mat.tolist()
        counts_l = counts.tolist()
        new_tokens = 0
        drafted = accepted = spec_slots = 0
        t_tok = time.monotonic()
        for i in occ:
            req = rep.slots[i]
            req.n_steps += 1
            if req.prompt_i < len(req.prompt):
                # teacher forcing: the program committed ONE position
                # (spec mask 0) — both models consumed the staged
                # prompt token; stage the next one
                rep.pos_np[i] += 1.0
                rep.tokens_np[i] = req.prompt[req.prompt_i]
                req.prompt_i += 1
                if req.prompt_i >= len(req.prompt):
                    rep.spec_np[i] = 1.0
                self._check_finish(rep, i)
                continue
            c = int(counts_l[i])
            spec_slots += 1
            drafted += self._spec_k
            accepted += c - 1
            cap = min(c, req.max_new - len(req.tokens),
                      self.max_len - int(rep.pos_np[i]))
            rep.pos_np[i] += float(c)
            evicted = False
            last = None
            for jj in range(cap):
                tok = int(toks_l[i][jj])
                req.tokens.append(tok)
                new_tokens += 1
                if req.t_first_tok is None:
                    req.t_first_tok = t_tok
                    if self._tm is not None:
                        self._tm.ttft.observe(t_tok - req.t_enqueue)
                req.t_last_tok = t_tok
                self._emit_token(req, tok)
                if req.on_token is not None \
                        and not self._fire_on_token(rep, req, tok):
                    evicted = True
                    break
                last = tok
                if self.eos_id is not None and tok == self.eos_id:
                    break
            if evicted:
                continue
            if last is not None:
                rep.tokens_np[i] = float(last)
            self._check_finish(rep, i)
        if spec_slots:
            with self._lock:
                self._spec_steps += 1
                self._spec_slot_steps += spec_slots
                self._spec_drafted += drafted
                self._spec_accepted += accepted
            if self._tm is not None and self._tm.spec_drafted \
                    is not None:
                self._tm.spec_drafted.inc(drafted)
                self._tm.spec_accepted.inc(accepted)
                self._tm.spec_rejected.inc(drafted - accepted)
                if drafted:
                    self._tm.spec_accept.observe(accepted
                                                 / float(drafted))
        return new_tokens

    def _check_finish(self, rep, slot):
        req = rep.slots[slot]
        if req is None or not req.tokens:
            return
        if self.eos_id is not None and req.tokens[-1] == self.eos_id:
            self._finish_slot(rep, slot, "eos")
        elif len(req.tokens) >= req.max_new:
            self._finish_slot(rep, slot, "length")
        elif rep.pos_np[slot] >= self.max_len:
            # no position left to consume the staged token at: the
            # fixed O(1) cache layout is full
            self._finish_slot(rep, slot, "length")

    def _finish_slot(self, rep, slot, reason):
        """Leave the batch between steps: deliver the result, mark the
        slot dead (valid=0) — its state rows stay as stale garbage,
        which the row-local step verdict proves harmless, and the next
        join rewrites them."""
        req = rep.slots[slot]
        rep.slots[slot] = None
        rep.valid_np[slot] = 0.0
        rep.tokens_np[slot] = 0.0
        rep.pos_np[slot] = 0.0
        rep.spec_np[slot] = 0.0
        now = time.monotonic()
        t1 = time.perf_counter()
        res = DecodeResult(req.tokens, reason, n_steps=req.n_steps,
                           prompt_len=len(req.prompt))
        if req.tenant is not None and req.uflops \
                and self._eff is not None:
            # flush the request's accumulated useful-FLOPs share to
            # its tenant series (tokens/outcome/latency ride the
            # future's done callback)
            self._eff.tenant_useful(req.tenant, req.uflops)
            req.uflops = 0
        if not req.future.cancelled():
            try:
                req.future.set_result(res)
            except Exception:
                pass
        with self._lock:
            self._leaves += 1
            self._requests_served += 1
            if reason == "deadline":
                self._evictions += 1
            self._lat_ms.append((now - req.t_enqueue) * 1e3)
        if self._tl is not None:
            self._tl.instant(
                "decode.evict" if reason == "deadline"
                else "decode.leave", "decode",
                "decode:%s" % rep.label,
                args={"slot": slot, "reason": reason,
                      "request": req.sse_id,
                      "tokens": len(req.tokens)})
        if self._tm is not None:
            self._tm.leave(reason)
            if reason == "deadline":
                self._tm.evictions.inc()
            if len(req.tokens) >= 2 and req.t_first_tok is not None \
                    and req.t_last_tok is not None:
                # mean inter-token gap over this request's generation:
                # one observation per request keeps the hot loop at
                # O(1) instrument calls while the histogram still
                # carries the per-request tail the counter cannot
                self._tm.tpot.observe(
                    (req.t_last_tok - req.t_first_tok)
                    / (len(req.tokens) - 1))
        if req.trace is not None:
            t_join = req.t_join if req.t_join is not None else t1

            def build(tc, _req=req, _t_join=t_join, _t1=t1,
                      _reason=reason):
                tc.add("queue-wait", tc.root.t0, _t_join, "serve")
                meta = {"steps": _req.n_steps,
                        "tokens": len(_req.tokens),
                        "prompt_len": len(_req.prompt),
                        "finish_reason": _reason}
                if _req.sse_id is not None:
                    # the request id joins the retained trace to its
                    # SSE stream and timeline token instants — the
                    # request_autopsy lookup key
                    meta["request"] = _req.sse_id
                tc.add("decode", _t_join, _t1, "serve", meta=meta)
            req.trace.finish(t1, build=build)

    # ------------------------------------------------------------ observe
    def warmup(self):
        """Compile everything live traffic will ever dispatch: the
        persistent step program, the per-state row-write kernels, and
        (with a prefill graph) one program per pow2 prompt bucket.
        After this, joins/leaves/steps never trace — tests pin
        ``compile_count`` across churn.  Returns the compile count.

        The step runs TWICE on purpose: jax's executable cache keys on
        argument sharding, and the kernel's own state outputs (every
        live iteration's inputs) carry committed shardings that fresh
        ``init_states`` buffers don't — one warm step would leave the
        first live iteration paying a silent ~100ms recompile that the
        trace counter cannot even see.  The row-write kernel likewise
        warms against both a fresh buffer and a stepped one (the two
        shardings a prefill scatter can meet)."""
        for rep in self._replicas:
            self._warm_replica(rep)
        return self.compile_count

    def _warm_replica(self, rep):
        """One replica's warm sequence — the docstring above is the
        contract; shared with ``rehabilitate()`` so a rehabilitated
        replica warms (and commits state shardings) exactly like a
        fresh one."""
        z = np.zeros((self.num_slots,), np.float32)
        prog = rep.program
        states = prog.init_states()
        states = prog.zero_row(states, 0)
        if self._spec_k:
            _t, _c, states = prog.step_spec(z, z, z, z, states)
            _t, _c, states = prog.step_spec(z, z, z, z, states)
        else:
            _, states = prog.step(z, z, z, states)
            _, states = prog.step(z, z, z, states)
        rows = {}
        # ALL states — the prefill path also scatters draft rows
        # (zero_row which="draft") into STEPPED buffers, and their
        # per-sharding row kernels must be warm too
        for key, info in prog._state_infos():
            dt = np.dtype(info.get("dtype") or prog._dtype)
            rows[key] = np.zeros(tuple(info["shape"]), dt)
        prog.write_row(states, 0, rows)
        for b in rep.prefill_buckets:
            # the full (batch, prompt) bucket grid: coalesced prefill
            # dispatches at pow2 BATCH extents too, and every shape
            # live traffic can meet must be warm or the zero-warm-
            # retrace contract would leak through the coalesced path
            for bb in self._prefill_batches:
                rep.prefill_caches[b].run({
                    self._prefill_data_name: np.zeros((bb, b),
                                                      np.float32),
                    self._prefill_len_name: np.zeros((bb,), np.float32)})

    @property
    def compile_count(self):
        c = 0
        seen = set()
        for rep in self._replicas:
            c += rep.program.trace_count
            for cache in rep.prefill_caches.values():
                if id(cache) not in seen:   # shared length-poly cache
                    seen.add(id(cache))
                    c += cache.compile_count
        return c

    def _spec_stats(self):
        """The ``stats()["decode"]["spec"]`` block — caller holds
        ``self._lock``.  ``accept_rate`` is lifetime accepted/drafted;
        ``tokens_per_step`` counts committed tokens per SLOT per
        speculative step (accepted drafts + the one target token
        every per-slot step yields; 1.0 floor, k+1 ceiling) — the
        same numbers the spec telemetry series carry."""
        if not self._spec_k:
            return {"enabled": False, "k": 0}
        drafted = self._spec_drafted
        steps = self._spec_steps
        return {
            "enabled": True,
            "k": self._spec_k,
            "draft_verdict": self.draft_verdict,
            "steps": steps,
            "drafted": drafted,
            "accepted": self._spec_accepted,
            "rejected": drafted - self._spec_accepted,
            "accept_rate": (self._spec_accepted / float(drafted)
                            if drafted else None),
            "tokens_per_step": ((self._spec_accepted
                                 + self._spec_slot_steps)
                                / float(self._spec_slot_steps)
                                if self._spec_slot_steps else None),
            "commit_selection": self._spec_cfg.selection,
            "commit_accepted": (bool(self._spec_cfg.commit_plan
                                     .accepted)
                                if self._spec_cfg.commit_plan
                                is not None else None),
            "draft_digest": self._spec_cfg.draft_digest,
        }

    def stats(self):
        """Admission counters plus the ``decode`` block: slot-pool
        geometry and occupancy, step/token/join/leave/eviction
        counts, per-step and end-to-end latency percentiles — the
        same numbers the ``mxnet_serve_decode_*`` series carry."""
        snap = self._adm.stats()
        # allocator peek outside the lock: device_memory_peak() can
        # stall on the backend, and a scrape must not block stepping
        mem = _memory_stats_block(self.memory_plan)
        with self._lock:
            step = sorted(self._step_ms)
            lat = sorted(self._lat_ms)
            snap["decode"] = {
                "slots": self.num_slots * len(self._replicas),
                "slots_per_replica": self.num_slots,
                "slots_occupied": self._occupied_count(),
                "max_len": self.max_len,
                "steps": self._steps,
                "tokens_generated": self._tokens_out,
                "joins": self._joins,
                "steals": self._steals,
                "leaves": self._leaves,
                "evictions": self._evictions,
                "requests_served": self._requests_served,
                "compile_count": self.compile_count,
                "sampler": self._sampler.describe(),
                "sharding": self._sharding_spec,
                "aot": (self._aot.stats() if self._aot is not None
                        else {"enabled": False}),
                "memory": mem,
                "efficiency": (self._eff.stats_block()
                               if self._eff is not None
                               else {"enabled": False}),
                "replicas": [r.describe() for r in self._replicas],
                "prefill": ("bucket" if self._prefill_caches
                            else "step"),
                "prefill_buckets": list(self._prefill_buckets),
                "prefill_coalesced": bool(self._coalesce),
                "prefill_batch_buckets": list(self._prefill_batches),
                "prefill_dispatches": self._prefill_dispatches,
                "optimizer": {
                    "accepted": (bool(self.opt_plan.accepted)
                                 if self.opt_plan is not None else None),
                    "rewrites": (len(self.opt_plan.rewrites)
                                 if self.opt_plan is not None
                                 and self.opt_plan.accepted else 0),
                    "reason": (self.opt_plan.reason
                               if self.opt_plan is not None else None),
                    "selection": self.selection,
                },
                "spec": self._spec_stats(),
                "step_ms": {
                    "count": len(step),
                    "mean": float(np.mean(step)) if step else 0.0,
                    "p50": _percentile(step, 0.50),
                    "p99": _percentile(step, 0.99),
                },
                "latency_ms": {
                    "count": len(lat),
                    "mean": float(np.mean(lat)) if lat else 0.0,
                    "p50": _percentile(lat, 0.50),
                    "p99": _percentile(lat, 0.99),
                },
            }
        snap["supervisor"] = _supervisor_state(self)
        snap["regulator"] = (self._regulator.stats()
                             if self._regulator is not None
                             else {"enabled": False})
        snap["faults"] = _faults.stats()
        return snap
