"""Data-parallel replica routing tests (mxnet_tpu/serving/replica.py).

Coverage per the issue contract: least-loaded routing with responses
BITWISE-identical to the single-replica engine (one-shot) and to
single-request greedy decode (decode, wherever a request seats),
replica failover — an induced dispatch failure drains the replica,
evicts its seated decode requests with PARTIAL output, keeps
co-resident replicas serving bitwise-identically, and dumps a flight
bundle — the reload-loop leak gate at N replicas (series, rules,
heartbeats, recorder refs all reclaimed at close()), the per-replica
``/healthz`` block + ``telemetry_dump healthz`` rendering, the
pluggable decode sampler (greedy bitwise-pinned, temperature/top-k on
the rng-key plumbing), the declarative alert-rules file, the
training-loop watchdog heartbeat, and the ``--replicas`` bench smokes
under a forced host device count.

Multi-replica engines here run their replicas on ONE device
(``ctx=[cpu(0), cpu(0)]``) — routing, failover, and telemetry are
device-count-independent, so the suite needs no XLA_FLAGS except in
the subprocess bench smoke.
"""
import importlib.util
import json
import os
import subprocess
import sys
import time
import urllib.request
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.serving import (DecodeEngine, ServingEngine, StepProgram,
                               greedy_decode, GreedySampler,
                               TemperatureSampler, replica_contexts)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_tool(name):
    path = os.path.join(REPO, "tools", "%s.py" % name)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mlp(feature=6, hidden=16, classes=4, seed=0):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.default_rng(seed)
    params = {
        "fc1_weight": mx.nd.array(
            rng.standard_normal((hidden, feature)).astype(np.float32)),
        "fc1_bias": mx.nd.zeros((hidden,)),
        "fc2_weight": mx.nd.array(
            rng.standard_normal((classes, hidden)).astype(np.float32)),
        "fc2_bias": mx.nd.zeros((classes,)),
    }
    return net, params


def _lstm_step(vocab=16, embed=8, hidden=16, seed=0):
    from mxnet_tpu.rnn.rnn_cell import LSTMCell
    tok = mx.sym.Variable("token")
    emb = mx.sym.Embedding(tok, input_dim=vocab, output_dim=embed,
                           name="emb")
    cell = LSTMCell(hidden, prefix="lstm_")
    out, (h2, c2) = cell(emb, [mx.sym.Variable("h"),
                               mx.sym.Variable("c")])
    logits = mx.sym.FullyConnected(out, num_hidden=vocab, name="out_fc")
    rng = np.random.default_rng(seed)

    def w(*shape, scale=0.5):
        return mx.nd.array(
            rng.standard_normal(shape).astype(np.float32) * scale)

    params = {
        "emb_weight": w(vocab, embed, scale=1.0),
        "lstm_i2h_weight": w(4 * hidden, embed),
        "lstm_i2h_bias": mx.nd.zeros((4 * hidden,)),
        "lstm_h2h_weight": w(4 * hidden, hidden),
        "lstm_h2h_bias": mx.nd.zeros((4 * hidden,)),
        "out_fc_weight": w(vocab, hidden, scale=1.0),
        "out_fc_bias": mx.nd.zeros((vocab,)),
    }
    step = mx.sym.Group([logits, h2, c2])
    state_info = [{"name": "h", "shape": (hidden,)},
                  {"name": "c", "shape": (hidden,)}]
    return step, params, state_info


@pytest.fixture
def _fresh_telemetry():
    telemetry.set_enabled(None)
    telemetry.reset()
    telemetry.stop_server()
    telemetry.stop_recorder()
    yield
    telemetry.stop_server()
    telemetry.stop_recorder()
    telemetry.set_enabled(None)
    telemetry.reset()


# ---------------------------------------------------------------------------
# replica_contexts resolution
# ---------------------------------------------------------------------------

def test_replica_contexts_resolution():
    # default single replica touches nothing
    assert replica_contexts(None, None) == [None]
    ctx = mx.cpu()
    assert replica_contexts(1, ctx) == [ctx]
    # explicit list IS the replica set (same device twice is legal)
    ctxs = replica_contexts(None, [mx.cpu(0), mx.cpu(0)])
    assert len(ctxs) == 2
    with pytest.raises(mx.base.MXNetError):
        replica_contexts(3, [mx.cpu(0), mx.cpu(0)])    # disagreement
    with pytest.raises(mx.base.MXNetError):
        replica_contexts(0, None)
    # explicit replicas beyond the device count refuse (this test env
    # has one CPU device unless XLA_FLAGS forced more)
    import jax
    n = jax.device_count()
    with pytest.raises(mx.base.MXNetError):
        replica_contexts(n + 1, None)


def test_env_replicas_clamp_warns(monkeypatch):
    import jax
    n = jax.device_count()
    monkeypatch.setenv("MXNET_SERVE_REPLICAS", str(n + 3))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ctxs = replica_contexts(None, None)
    assert len(ctxs) == n
    assert any("clamping" in str(x.message) for x in w)


# ---------------------------------------------------------------------------
# one-shot engine: routing, bitwise identity, failover
# ---------------------------------------------------------------------------

def test_serving_replicas_route_and_match_single():
    net, params = _mlp()
    rng = np.random.default_rng(1)
    X = rng.standard_normal((24, 6)).astype(np.float32)
    e1 = ServingEngine(net, params, {}, {"data": (6,)}, ctx=mx.cpu())
    e1.warmup()
    e2 = ServingEngine(net, params, {}, {"data": (6,)},
                       ctx=[mx.cpu(0), mx.cpu(0)])
    w2 = e2.warmup()
    ref = [e1.predict(x, timeout=60) for x in X]
    futs = [e2.submit(x) for x in X]
    got = [f.result(timeout=60) for f in futs]
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
    st = e2.stats()
    assert len(st["replicas"]) == 2
    assert all(r["healthy"] for r in st["replicas"])
    # both replicas actually dispatched (least-loaded routing spreads
    # a stream of single-request batches)
    assert all(r["batches"] >= 1 for r in st["replicas"])
    assert sum(r["batches"] for r in st["replicas"]) == st["batches"]
    assert e2.compile_count == w2 and st["retraces"] == 0
    e1.close()
    e2.close()


def test_serving_replica_failover(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    net, params = _mlp()
    eng = ServingEngine(net, params, {}, {"data": (6,)},
                        ctx=[mx.cpu(0), mx.cpu(0)])
    eng.warmup()
    x = np.ones((6,), np.float32)
    want = eng.predict(x, timeout=60)          # healthy baseline

    boom = RuntimeError("induced dispatch failure")
    real_run = eng._replicas[0].cache.run

    def bad_run(feeds, _record=True):
        raise boom
    eng._replicas[0].cache.run = bad_run
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        # an idle fleet routes to replica 0 first (index breaks the
        # tie) — this request eats the failure
        with pytest.raises(RuntimeError, match="induced dispatch"):
            eng.predict(x, timeout=60)
        # replica 0 is drained + unhealthy; traffic re-routes and the
        # co-resident replica keeps serving bitwise-identically
        for _ in range(3):
            np.testing.assert_array_equal(
                eng.predict(x, timeout=60), want)
    st = eng.stats()
    assert [r["healthy"] for r in st["replicas"]] == [False, True]
    assert st["replicas"][0]["failures"] == 1
    hb = eng._heartbeat()
    assert hb["replicas"][0]["healthy"] is False
    # the flight recorder dumped on the unhealthy transition — on the
    # REPLICA thread, after the client's future already failed, so
    # give the (registry-size-dependent) bundle write a bounded wait
    deadline = time.monotonic() + 30
    bundles = []
    while not bundles and time.monotonic() < deadline:
        bundles = [p for p in os.listdir(str(tmp_path))
                   if p.startswith("flight_")]
        if not bundles:
            time.sleep(0.02)
    assert bundles, "no flight bundle written on replica failure"
    doc = json.load(open(os.path.join(str(tmp_path), bundles[0])))
    assert "replica_failed" in doc["reason"]
    eng._replicas[0].cache.run = real_run
    eng.close()


def test_serving_all_replicas_unhealthy_fails_fast():
    net, params = _mlp()
    eng = ServingEngine(net, params, {}, {"data": (6,)},
                        ctx=[mx.cpu(0), mx.cpu(0)])
    eng.warmup()
    for rep in eng._replicas:
        rep.cache.run = lambda feeds, _record=True: (
            (_ for _ in ()).throw(RuntimeError("dead")))
    x = np.ones((6,), np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(RuntimeError, match="dead"):
            eng.predict(x, timeout=60)
        with pytest.raises(RuntimeError, match="dead"):
            eng.predict(x, timeout=60)
        # with every replica drained, new work fails fast instead of
        # wedging the queue
        with pytest.raises(mx.base.MXNetError, match="unhealthy"):
            eng.predict(x, timeout=60)
    eng.close()


def test_serving_replica_router_keeps_backpressure():
    """The router's per-replica in-flight cap keeps overload backlog in
    the ADMISSION queue, where max_queue backpressure still applies —
    an unbounded replica pending queue would silently disable
    QueueFullError/shed/deadline sweeps for every routed request."""
    net, params = _mlp()
    eng = ServingEngine(net, params, {}, {"data": (6,)},
                        ctx=[mx.cpu(0), mx.cpu(0)],
                        max_queue=4, batch_timeout_ms=0.0)
    eng.warmup()
    gate = __import__("threading").Event()
    real = {r.index: r.cache.run for r in eng._replicas}

    def slow_run(feeds, _record=True, _i=0):
        gate.wait(timeout=30)
        return real[_i](feeds, _record=_record)
    for rep in eng._replicas:
        rep.cache.run = (lambda feeds, _record=True, _i=rep.index:
                         slow_run(feeds, _record, _i))
    futs, rejected = [], 0
    for i in range(64):
        try:
            futs.append(eng.submit(np.full((6,), i, np.float32)))
        except serving.QueueFullError:
            rejected += 1
    assert rejected > 0, ("router drained the admission queue "
                          "unboundedly — backpressure never engaged")
    gate.set()
    for f in futs:
        f.result(timeout=60)
    eng.close()


# ---------------------------------------------------------------------------
# decode engine: pinning, bitwise identity, failover with partial output
# ---------------------------------------------------------------------------

def test_decode_replicas_bitwise_vs_greedy_reference():
    step, params, state_info = _lstm_step()
    ref_prog = StepProgram(step, params, {}, state_info, num_slots=1)
    want = {p: list(greedy_decode(ref_prog, [p], 6)) for p in range(4)}
    eng = DecodeEngine(step, params, {}, state_info, num_slots=2,
                       max_len=32, default_deadline_ms=0,
                       ctx=[mx.cpu(0), mx.cpu(0)])
    warm = eng.warmup()
    futs = [eng.submit([p], max_new_tokens=6) for p in range(4)]
    res = [f.result(timeout=120) for f in futs]
    for p, r in enumerate(res):
        assert r.finish_reason == "length"
        assert list(r.tokens) == want[p], "replica routing changed tokens"
    assert eng.compile_count == warm        # zero retraces across churn
    st = eng.stats()["decode"]
    assert st["slots"] == 4 and st["slots_per_replica"] == 2
    assert len(st["replicas"]) == 2
    assert st["joins"] == 4 and st["leaves"] == 4
    eng.close()


def test_decode_replica_failover_partial_output(tmp_path, monkeypatch):
    """An induced step failure on one replica evicts its seated
    requests with PARTIAL output (finish_reason 'error'); co-resident
    replicas keep serving bitwise-identically; the engine keeps
    accepting work afterwards."""
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    step, params, state_info = _lstm_step()
    ref_prog = StepProgram(step, params, {}, state_info, num_slots=1)
    want = {p: list(greedy_decode(ref_prog, [p], 30)) for p in (1, 2, 5)}
    eng = DecodeEngine(step, params, {}, state_info, num_slots=1,
                       max_len=64, default_deadline_ms=0,
                       ctx=[mx.cpu(0), mx.cpu(0)])
    eng.warmup()
    # one slot per replica: the router seats request 1 on replica 0,
    # request 2 on replica 1 (most-free, index-tied)
    f1 = eng.submit([1], max_new_tokens=30)
    f2 = eng.submit([2], max_new_tokens=30)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(r.occupied_count() == 1 for r in eng._replicas):
            break
        time.sleep(0.002)
    assert all(r.occupied_count() == 1 for r in eng._replicas)
    victim = eng._replicas[0].slots[0]
    assert victim is not None

    def bad_step(tokens, pos, valid, states, reset=None):
        raise RuntimeError("induced step failure")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        eng._replicas[0].program.step = bad_step
        r1 = f1.result(timeout=120)
        r2 = f2.result(timeout=120)
    # the victim: partial output, eviction reason, not an exception
    assert r1.finish_reason == "error"
    assert 0 < len(r1.tokens) < 30
    assert list(r1.tokens) == want[1][:len(r1.tokens)], \
        "partial output must be a prefix of the greedy reference"
    # the co-resident replica finished bitwise-identically
    assert r2.finish_reason == "length" and list(r2.tokens) == want[2]
    assert [r.healthy for r in eng._replicas] == [False, True]
    # new work lands on the survivor
    r3 = eng.submit([5], max_new_tokens=30).result(timeout=120)
    assert list(r3.tokens) == want[5]
    # bounded wait: the bundle is written on the failed replica's
    # thread, concurrent with the survivor serving the asserts above
    deadline = time.monotonic() + 30
    bundles = []
    while not bundles and time.monotonic() < deadline:
        bundles = [p for p in os.listdir(str(tmp_path))
                   if p.startswith("flight_")]
        if not bundles:
            time.sleep(0.02)
    assert bundles and "replica_failed" in json.load(
        open(os.path.join(str(tmp_path), bundles[0])))["reason"]
    eng.close()


def test_decode_routed_requests_reroute_off_failed_replica():
    """Requests routed to (but not yet seated on) a failing replica
    re-route to its siblings instead of being lost."""
    step, params, state_info = _lstm_step()
    eng = DecodeEngine(step, params, {}, state_info, num_slots=2,
                       max_len=32, default_deadline_ms=0,
                       ctx=[mx.cpu(0), mx.cpu(0)], start=False)
    eng.warmup()
    futs = [eng.submit([p % 8], max_new_tokens=3) for p in range(8)]
    calls = [0]
    real_step = eng._replicas[0].program.step

    def flaky_step(tokens, pos, valid, states, reset=None):
        calls[0] += 1
        if calls[0] >= 2:
            raise RuntimeError("late step failure")
        return real_step(tokens, pos, valid, states, reset=reset)
    eng._replicas[0].program.step = flaky_step
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        eng.start()
        res = [f.result(timeout=120) for f in futs]
    by_reason = {}
    for r in res:
        by_reason.setdefault(r.finish_reason, 0)
        by_reason[r.finish_reason] += 1
    # every future resolved: the evicted ones with "error", everything
    # else (including re-routed pendings) ran to completion
    assert sum(by_reason.values()) == 8
    assert by_reason.get("length", 0) >= 6
    eng.close()


# ---------------------------------------------------------------------------
# reload-loop leak gate at N replicas
# ---------------------------------------------------------------------------

def test_reload_loop_leak_gate_with_replicas(_fresh_telemetry):
    reg = telemetry.registry()
    mgr = telemetry.default_manager()
    net, params = _mlp()
    step, sparams, state_info = _lstm_step()
    rules0 = len(mgr)
    for _ in range(3):
        se = ServingEngine(net, params, {}, {"data": (6,)},
                           ctx=[mx.cpu(0), mx.cpu(0)])
        de = DecodeEngine(step, sparams, {}, state_info, num_slots=2,
                          max_len=32, default_deadline_ms=0,
                          ctx=[mx.cpu(0), mx.cpu(0)])
        se.warmup()
        de.warmup()
        se.predict(np.ones((6,), np.float32), timeout=60)
        de.generate([1], max_new_tokens=2, timeout=120)
        se.close()
        de.close()
        # timeline plane (ISSUE 20): both engines drop their ring
        # reference at close(); the bounded ring itself is process-
        # wide and must never exceed its capacity across reloads
        assert se._tl is None and de._tl is None
        tl = telemetry.timeline.peek()
        assert tl is None or len(tl.events()) <= tl.capacity
    # every per-engine AND per-replica series reclaimed
    for fam_name in ("mxnet_serve_replica_healthy",
                     "mxnet_serve_replica_inflight",
                     "mxnet_serve_replica_failures_total",
                     "mxnet_serve_replica_batches_total",
                     "mxnet_serve_replicas",
                     "mxnet_serve_dispatch_ms",
                     "mxnet_serve_batch_occupancy",
                     "mxnet_serve_retraces_total",
                     "mxnet_serve_decode_slots",
                     "mxnet_serve_decode_slots_occupied",
                     "mxnet_serve_decode_step_ms",
                     "mxnet_serve_memory_predicted_peak_bytes",
                     "mxnet_serve_memory_measured_peak_bytes",
                     "mxnet_serve_queue_depth",
                     # serving efficiency plane (ISSUE 18): every
                     # engine-labeled ledger/gauge/tenant series
                     "mxnet_serve_flops_total",
                     "mxnet_serve_flops_useful_total",
                     "mxnet_serve_flops_padding_total",
                     "mxnet_serve_flops_dead_slot_total",
                     "mxnet_serve_flops_spec_rejected_total",
                     "mxnet_serve_unpriced_dispatches_total",
                     "mxnet_serve_mfu",
                     "mxnet_serve_goodput_ratio",
                     "mxnet_serve_tenant_useful_flops_total",
                     "mxnet_serve_tenant_tokens_total",
                     "mxnet_serve_tenant_requests_total",
                     "mxnet_serve_tenant_latency_ms",
                     "mxnet_serve_tenant_overflow_total"):
        fam = reg.get(fam_name)
        assert fam is None or fam.series() == [], fam_name
    assert reg._callbacks == []
    assert len(mgr) == rules0
    assert telemetry.heartbeats() == {}
    assert telemetry.get_recorder() is None
    # second, independent gate (PR 19): the STATIC reclaim-pairing
    # lint must agree that every dynamic-label series has a close()-
    # reachable reclaim — a series-without-reclaim regression now
    # fails here even if the runtime loop above misses its family
    from mxnet_tpu.analysis import analyze_concurrency
    model = analyze_concurrency()
    leaks = [d for d in model.report.to_list()
             if d["pass"] == "lifecycle"
             and d["node"] != "telemetry.sampling:SamplerChain"]
    assert leaks == [], leaks


# ---------------------------------------------------------------------------
# healthz per-replica block + telemetry_dump healthz
# ---------------------------------------------------------------------------

def test_healthz_replica_block_and_cli(_fresh_telemetry, capsys):
    net, params = _mlp()
    srv = telemetry.start_server(0, host="127.0.0.1")
    eng = ServingEngine(net, params, {}, {"data": (6,)},
                        ctx=[mx.cpu(0), mx.cpu(0)])
    eng.warmup()
    for i in range(4):
        eng.predict(np.full((6,), i, np.float32), timeout=60)
    url = "http://127.0.0.1:%d" % srv.port
    with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
        hz = json.loads(r.read().decode())
    el = eng._tm.engine_label
    block = hz["replicas"]
    assert block["total"] == 2 and block["unhealthy"] == 0
    rows = block["engines"][el]
    assert [r["replica"] for r in rows] == ["0", "1"]
    assert all(r["healthy"] for r in rows)
    assert sum(r.get("batches", 0) for r in rows) == eng.stats()["batches"]
    # the CLI renders the same block
    telemetry_dump = _import_tool("telemetry_dump")
    assert telemetry_dump.main(["healthz", "--url", url]) == 0
    out = capsys.readouterr().out
    assert "replicas: 2 total, 0 unhealthy" in out
    assert "engine" in out and "ok" in out
    eng.close()
    # reclaimed with the engine: the block disappears
    with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
        hz = json.loads(r.read().decode())
    assert "replicas" not in hz
    telemetry.stop_server()


# ---------------------------------------------------------------------------
# pluggable decode sampler
# ---------------------------------------------------------------------------

def test_sampler_topk1_is_argmax_bitwise():
    step, params, state_info = _lstm_step()
    ref = StepProgram(step, params, {}, state_info, num_slots=1)
    want = list(greedy_decode(ref, [3], 8))
    sp = StepProgram(step, params, {}, state_info, num_slots=1,
                     sampler=TemperatureSampler(temperature=2.0,
                                                top_k=1, seed=123))
    got = list(greedy_decode(sp, [3], 8))
    assert got == want, "top_k=1 must degenerate to argmax"


def test_sampler_seeded_replay_and_zero_retraces():
    step, params, state_info = _lstm_step()

    def run_once():
        eng = DecodeEngine(step, params, {}, state_info, num_slots=2,
                           max_len=32, default_deadline_ms=0,
                           sampler=TemperatureSampler(1.3, top_k=4,
                                                      seed=11))
        warm = eng.warmup()
        futs = [eng.submit([p], max_new_tokens=6) for p in (1, 2, 3)]
        toks = [list(f.result(timeout=120).tokens) for f in futs]
        assert eng.compile_count == warm    # churn never retraces
        st = eng.stats()["decode"]
        assert st["sampler"]["kind"] == "temperature"
        eng.close()
        return toks
    a = run_once()
    b = run_once()
    assert a == b, "fixed seed must replay bitwise"
    flat = [t for toks in a for t in toks]
    assert all(0 <= t < 16 for t in flat)
    assert len(flat) == 18


def test_sampler_greedy_default_describes():
    step, params, state_info = _lstm_step()
    eng = DecodeEngine(step, params, {}, state_info, num_slots=1,
                       max_len=32, default_deadline_ms=0)
    assert eng.stats()["decode"]["sampler"] == {"kind": "greedy"}
    assert isinstance(eng._sampler, GreedySampler)
    eng.close()
    with pytest.raises(mx.base.MXNetError):
        TemperatureSampler(temperature=0.0)
    with pytest.raises(mx.base.MXNetError):
        TemperatureSampler(top_k=0)


# ---------------------------------------------------------------------------
# declarative alert rules file
# ---------------------------------------------------------------------------

def test_alert_rules_file_loads_and_is_idempotent(tmp_path, monkeypatch,
                                                  _fresh_telemetry):
    rules = [
        {"name": "ops_queue_depth_high", "kind": "threshold",
         "series": "mxnet_serve_queue_depth", "query": "latest",
         "op": ">", "threshold": 100.0, "severity": "ticket",
         "annotations": {"summary": "queue building"}},
        {"name": "broken_rule", "kind": "no_such_kind"},
    ]
    path = tmp_path / "rules.json"
    path.write_text(json.dumps(rules))
    monkeypatch.setenv("MXNET_TELEMETRY_ALERT_RULES", str(path))
    mgr = telemetry.default_manager()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        added = telemetry.load_rules_file()
    assert [r.name for r in added] == ["ops_queue_depth_high"]
    assert any("invalid" in str(x.message) for x in w)
    rule = added[0]
    assert rule.annotations["source"] == str(path)
    assert len(mgr) == 1
    # idempotent reload (every engine-driven recorder rebuild re-runs it)
    assert telemetry.load_rules_file() == []
    assert len(mgr) == 1
    mgr.remove_rule("ops_queue_depth_high")

    # the recorder build path loads it too — operator SLOs are live the
    # moment something starts evaluating
    rec = telemetry.start_recorder(interval_s=30.0, window=10)
    try:
        assert any(r.name == "ops_queue_depth_high"
                   for r in mgr.rules())
        assert rec.alerts is mgr
    finally:
        telemetry.stop_recorder()
        mgr.remove_rule("ops_queue_depth_high")


def test_alert_rules_file_malformed_warns_not_raises(tmp_path,
                                                     monkeypatch):
    path = tmp_path / "broken.json"
    path.write_text("{not json")
    monkeypatch.setenv("MXNET_TELEMETRY_ALERT_RULES", str(path))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert telemetry.load_rules_file() == []
    assert any("cannot load" in str(x.message) for x in w)
    monkeypatch.setenv("MXNET_TELEMETRY_ALERT_RULES",
                       str(tmp_path / "absent.json"))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert telemetry.load_rules_file() == []
    assert any("cannot load" in str(x.message) for x in w)


# ---------------------------------------------------------------------------
# training-loop watchdog
# ---------------------------------------------------------------------------

def test_steptimer_heartbeat_and_watchdog(_fresh_telemetry):
    from mxnet_tpu.telemetry.step import StepTimer
    mgr = telemetry.default_manager()
    st = StepTimer(loop="wdtest")
    try:
        hbs = telemetry.heartbeats()
        assert "train.wdtest" in hbs
        assert hbs["train.wdtest"]["busy"] is False    # no step open
        rules = {r.name: r for r in mgr.rules()}
        assert "train_wdtest_stalled" in rules
        st.begin_step()
        hb = telemetry.heartbeats()["train.wdtest"]
        assert hb["busy"] is True and hb["kind"] == "train"
        # the watchdog rule reads the same heartbeat: a wedged open
        # step (no progress past the threshold) is active
        rule = rules["train_wdtest_stalled"]
        active, _, _ = rule.evaluate(
            None, heartbeats={"train.wdtest": {"busy": True,
                                               "age_s": 1e9}})
        assert active is True
        active, _, _ = rule.evaluate(
            None, heartbeats={"train.wdtest": {"busy": False,
                                               "age_s": 1e9}})
        assert active is False              # idle loop never pages
        st.end_step()
        assert telemetry.heartbeats()["train.wdtest"]["busy"] is False
    finally:
        st.close()
    assert "train.wdtest" not in telemetry.heartbeats()
    assert not any(r.name == "train_wdtest_stalled" for r in mgr.rules())


def test_steptimer_shared_watchdog_refcounts(_fresh_telemetry):
    from mxnet_tpu.telemetry.step import StepTimer
    mgr = telemetry.default_manager()
    a = StepTimer(loop="wdshare")
    b = StepTimer(loop="wdshare")       # same loop label: one rule
    assert sum(1 for r in mgr.rules()
               if r.name == "train_wdshare_stalled") == 1
    a.close()
    assert any(r.name == "train_wdshare_stalled" for r in mgr.rules())
    b.close()
    assert not any(r.name == "train_wdshare_stalled"
                   for r in mgr.rules())


# ---------------------------------------------------------------------------
# bench smoke under a forced host device count (tier-1, subprocess:
# XLA_FLAGS must be set before jax initializes)
# ---------------------------------------------------------------------------

def test_replica_bench_smoke_forced_devices():
    code = """
import sys
sys.path.insert(0, %r)
sys.path.insert(0, %r)
import serve_bench, decode_bench
row = serve_bench.run_replica_sweep(
    requests=48, repeats=1, replica_counts=(1, 2), hidden=32, layers=1)
assert row["device_count"] >= 2, row
assert row["retraces"] == 0, row
assert row["bitwise_identical"], row
assert [r["replicas"] for r in row["rows"]] == [1, 2]
row2 = decode_bench.run_replica_sweep(
    requests=8, slots=2, max_len=16, mean_new=4, hidden=8,
    repeats=1, replica_counts=(1, 2))
assert row2["retraces"] == 0, row2
assert row2["bitwise_identical"], row2
print("REPLICA_SMOKE_OK")
""" % (REPO, os.path.join(REPO, "perf"))
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("MXNET_TELEMETRY_PORT", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "REPLICA_SMOKE_OK" in out.stdout
