"""Checkpoint format + kvstore wiring helpers (+ legacy FeedForward).

Reference: python/mxnet/model.py — _create_kvstore:58, save_checkpoint:366
(`prefix-symbol.json` + `prefix-%04d.params`), load_checkpoint:396,
FeedForward:899 (deprecated in favor of Module).
"""
from __future__ import annotations

import logging
from collections import namedtuple

from . import ndarray as nd
from . import symbol as sym
from .base import MXNetError, string_types
from .context import cpu, current_context
from .initializer import Uniform

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from --kv-store string (model.py:58).

    Returns (kvstore, update_on_kvstore).  On TPU a single process drives all
    local devices through one sharded executor, so `device`≡`local`; the
    reference's heuristics (big-array bound etc.) collapse away.
    """
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, string_types):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Write `prefix-symbol.json` + `prefix-%04d.params` (model.py:366)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v.as_in_context(cpu())
                 for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v.as_in_context(cpu())
                      for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) (model.py:396)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward(object):
    """Legacy training API (model.py:899) — thin adapter over Module."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=Uniform(0.01), numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        logging.warning("\033[91mmxnet_tpu.model.FeedForward has been "
                        "deprecated. Please use mxnet_tpu.mod.Module "
                        "instead.\033[0m")
        self.symbol = symbol
        self.ctx = ctx if ctx is not None else current_context()
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None

    def _init_module(self, data):
        from .module import Module
        label_names = [n for n in self.symbol.list_arguments()
                       if n.endswith("label")]
        self._module = Module(
            self.symbol,
            data_names=[d.name for d in data.provide_data],
            label_names=label_names or None,
            context=self.ctx)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .io import NDArrayIter
        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, y, batch_size=self.numpy_batch_size)
        self._init_module(X)
        self._module.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         kvstore=kvstore, optimizer=self.optimizer,
                         optimizer_params=self.kwargs,
                         initializer=self.initializer,
                         arg_params=self.arg_params,
                         aux_params=self.aux_params,
                         begin_epoch=self.begin_epoch,
                         num_epoch=self.num_epoch)
        self.arg_params, self.aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from .io import NDArrayIter
        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, batch_size=self.numpy_batch_size)
        if self._module is None or not self._module.binded:
            self._init_module(X)
            self._module.bind(X.provide_data, X.provide_label or None,
                              for_training=False)
            self._module.init_params(arg_params=self.arg_params,
                                     aux_params=self.aux_params)
        out = self._module.predict(X, num_batch=num_batch, reset=reset)
        if isinstance(out, list):
            return [o.asnumpy() for o in out]
        return out.asnumpy()

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
