"""tools/launch.py scheduler trackers (VERDICT r3 missing #4).

Reference: tools/launch.py + dmlc_tracker {local,ssh,mpi,sge,yarn}.  The
mpi/sge/yarn modes build scheduler submit commands carrying the DMLC_*
env contract with a per-rank DMLC_WORKER_ID shim; --dry-run prints the
command, which is what CI can verify without a cluster.
"""
import os
import subprocess
import sys

LAUNCH = os.path.join(os.path.dirname(__file__), "..", "tools", "launch.py")


def _dry_run(launcher, extra=()):
    out = subprocess.run(
        [sys.executable, LAUNCH, "-n", "4", "--launcher", launcher,
         "--root-host", "head0", "--port", "29999", "--dry-run",
         *extra, "python", "train.py", "--lr", "0.1"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    return out.stdout.strip()


def test_mpi_dry_run():
    cmd = _dry_run("mpi")
    assert cmd.startswith("mpirun")
    assert "-n 4" in cmd
    assert "DMLC_PS_ROOT_URI=head0" in cmd
    assert "DMLC_PS_ROOT_PORT=29999" in cmd
    assert "DMLC_NUM_WORKER=4" in cmd
    assert "OMPI_COMM_WORLD_RANK" in cmd  # per-rank worker-id shim
    assert "python train.py --lr 0.1" in cmd


def test_sge_dry_run():
    cmd = _dry_run("sge", extra=("--queue", "gpu.q"))
    assert cmd.startswith("qsub")
    assert "-t 1-4" in cmd
    assert "-q gpu.q" in cmd
    assert "DMLC_NUM_WORKER=4" in cmd
    assert "SGE_TASK_ID" in cmd


def test_yarn_dry_run():
    cmd = _dry_run("yarn")
    assert cmd.startswith("yarn jar")
    assert "-num_containers 4" in cmd
    assert "DMLC_PS_ROOT_URI=head0" in cmd
    assert "YARN_SHELL_ID" in cmd  # the distributed-shell rank variable
    assert "python train.py --lr 0.1" in cmd


def test_mpi_hostfile_and_quoting(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("h0\nh1\n")
    out = subprocess.run(
        [sys.executable, LAUNCH, "-n", "4", "--launcher", "mpi",
         "--root-host", "head0", "--dry-run", "-H", str(hf),
         "python", "train.py", "--tag", "run 1"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    cmd = out.stdout.strip()
    assert "--hostfile %s" % hf in cmd
    # args with spaces survive the bash -c shim (shlex quoting)
    assert "'run 1'" in cmd


def test_parse_log_tool(tmp_path):
    """tools/parse_log.py: fit()-style log -> per-epoch table (reference
    tools/parse_log.py surface, + tsv/json)."""
    import json
    import subprocess
    import sys
    log = tmp_path / "train.log"
    log.write_text("\n".join([
        "INFO Epoch[0] Train-accuracy=0.5",
        "INFO Epoch[0] Validation-accuracy=0.4",
        "INFO Epoch[0] Time cost=10.0",
        "INFO Epoch[1] Train-accuracy=0.8",
        "INFO Epoch[1] Validation-accuracy=0.7",
        "INFO Epoch[1] Time cost=9.0",
        "noise line",
    ]))
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "parse_log.py")
    out = subprocess.run([sys.executable, tool, str(log), "--format",
                          "json"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    data = json.loads(out.stdout)
    assert data["epochs"]["1"]["train-accuracy"] == 0.8
    assert data["epochs"]["0"]["time"] == 10.0
    md = subprocess.run([sys.executable, tool, str(log)],
                        capture_output=True, text=True).stdout
    assert "| epoch |" in md and "0.7" in md


def test_kill_jobs_tool(tmp_path):
    """tools/kill_jobs.py: kills processes matched by command-line
    substring (reference tools/kill-mxnet.py surface), local mode."""
    import subprocess
    import sys
    import time
    marker = "mxtpu_kill_test_%d" % os.getpid()
    victim = subprocess.Popen(
        [sys.executable, "-c",
         "import sys, time; time.sleep(300)  # " + marker, marker])
    try:
        time.sleep(0.5)
        tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "kill_jobs.py")
        out = subprocess.run([sys.executable, tool, marker],
                             capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        try:
            rc = victim.wait(timeout=10)
        except subprocess.TimeoutExpired:
            raise AssertionError("victim survived; tool said: %r / %r"
                                 % (out.stdout, out.stderr))
        assert rc != 0                      # SIGKILLed
    finally:
        if victim.poll() is None:
            victim.kill()


def test_tensorboard_callback(tmp_path):
    """contrib.tensorboard.LogMetricsCallback streams metric values
    (reference python/mxnet/contrib/tensorboard.py surface)."""
    import mxnet_tpu as mx
    from mxnet_tpu.contrib.tensorboard import LogMetricsCallback

    class Param:
        pass
    metric = mx.metric.create("acc")
    import numpy as np
    metric.update([mx.nd.array(np.array([0, 1]))],
                  [mx.nd.array(np.array([[0.9, 0.1], [0.2, 0.8]]))])
    cb = LogMetricsCallback(str(tmp_path / "tb"))
    p = Param()
    p.eval_metric = metric
    cb(p)
    cb(p)
    cb.close()
    files = list((tmp_path / "tb").iterdir())
    assert files, "no event files written"
