"""Weight initializers.

Reference: python/mxnet/initializer.py — registry + magic-name dispatch
(InitDesc carries the param name; `_weight` → weight init, `_bias` → zeros,
`_gamma` → ones, ... ), Uniform/Normal/Xavier/MSRAPrelu/Orthogonal/Bilinear/
LSTMBias/One/Zero/Constant/Load/Mixed.

Initialization runs host-side with numpy then lands on device — init is a
one-time cost, and numpy keeps the reference's exact RNG-free semantics for
deterministic inits (Bilinear, LSTMBias) while random inits use the global
numpy seed exactly like the reference.
"""
from __future__ import annotations

import json
import logging
import math

import numpy as np

from .base import MXNetError, string_types
from .ndarray import NDArray, array, load

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Xavier",
           "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias", "One", "Zero",
           "Constant", "Load", "Mixed", "FusedRNN", "register", "create"]

_INIT_REGISTRY = {}


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (initializer.py:37)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


def register(klass):
    """Register an initializer under its lowercase class name."""
    name = klass.__name__.lower()
    if name in _INIT_REGISTRY:
        logging.warning("New initializer %s is overriding existing "
                        "initializer %s", klass.__name__, name)
    _INIT_REGISTRY[name] = klass
    klass._init_name = name
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if callable(name):
        return name
    if name.startswith("["):
        # JSON produced by Initializer.dumps() (stored in the __init__ attr
        # by sym.Variable(init=...))
        klass, kw = json.loads(name)
        return _INIT_REGISTRY[klass.lower()](**kw)
    name = name.lower()
    if name not in _INIT_REGISTRY:
        raise MXNetError("unknown initializer %r" % name)
    return _INIT_REGISTRY[name](**kwargs)


class Initializer(object):
    """Base initializer with magic-name dispatch (initializer.py:68)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        if print_func is None:
            def asum_stat(x):
                return str((np.abs(x.asnumpy()).mean(),))
            print_func = asum_stat
        self._print_func = print_func
        return self

    def _verbose_print(self, desc, init, arr):
        if self._verbose and self._print_func:
            logging.info("Initialized %s as %s: %s", desc, init,
                         self._print_func(arr))

    def dumps(self):
        """JSON [name, kwargs] — the reference's serialization for sending
        the initializer to kvstore servers."""
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, string_types):
            raise TypeError("desc must be an InitDesc or string")
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)

        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")

        if init:
            create(init)._init_weight(desc, arr)
            self._verbose_print(desc, init, arr)
            return
        # magic-name dispatch
        if desc.endswith("weight"):
            self._init_weight(desc, arr)
            self._verbose_print(desc, "weight", arr)
        elif desc.endswith("bias"):
            self._init_bias(desc, arr)
            self._verbose_print(desc, "bias", arr)
        elif desc.endswith("gamma"):
            self._init_gamma(desc, arr)
            self._verbose_print(desc, "gamma", arr)
        elif desc.endswith("beta"):
            self._init_beta(desc, arr)
            self._verbose_print(desc, "beta", arr)
        elif desc.endswith("min"):
            self._init_zero(desc, arr)
            self._verbose_print(desc, "min", arr)
        elif desc.endswith("max"):
            self._init_one(desc, arr)
            self._verbose_print(desc, "max", arr)
        elif desc.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif desc.endswith("moving_var"):
            self._init_one(desc, arr)
        elif desc.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif desc.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _set(self, arr, value):
        arr[:] = value

    def _init_bias(self, _, arr):
        self._set(arr, 0.0)

    def _init_gamma(self, _, arr):
        self._set(arr, 1.0)

    def _init_beta(self, _, arr):
        self._set(arr, 0.0)

    def _init_zero(self, _, arr):
        self._set(arr, 0.0)

    def _init_one(self, _, arr):
        self._set(arr, 1.0)

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_default(self, name, _):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to \"weight\", \"bias\", \"gamma\" (1.0), and "
            "\"beta\" (0.0). Please use mx.sym.Variable(init=mx.init.*) to "
            "set initialization pattern" % name)


@register
class Zero(Initializer):
    def __init__(self):
        super().__init__()

    def _init_weight(self, _, arr):
        self._set(arr, 0.0)


@register
class One(Initializer):
    def __init__(self):
        super().__init__()

    def _init_weight(self, _, arr):
        self._set(arr, 1.0)


# reference registers these plural aliases (initializer.py @register alias)
_INIT_REGISTRY["zeros"] = Zero
_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._set(arr, self.value)


@register
class Uniform(Initializer):
    """U(-scale, scale) (initializer.py Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale,
                                   arr.shape).astype(arr.dtype)


@register
class Normal(Initializer):
    """N(0, sigma) (initializer.py Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape).astype(arr.dtype)


@register
class Load(object):
    """Init from a dict/file of arrays, falling back to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise ValueError(
                    "Parameter %s cannot be initialized from loading. "
                    "Shape mismatch, target %s vs loaded %s"
                    % (name, str(arr.shape), str(self.param[name].shape)))
            arr[:] = self.param[name].asnumpy()
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise ValueError(
                    "Cannot Initialize parameter %s. Not found in loaded "
                    "param and no default initialization is provided." % name)
            self.default_init(name, arr)
            if self.verbose:
                logging.info("Initialized %s by default", name)


@register
class Mixed(object):
    """Regex-pattern dispatch to multiple initializers."""

    def __init__(self, patterns, initializers):
        import re
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must have the same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(
            "Parameter name %s did not match any pattern. Consider adding a "
            "\".*\" pattern at the and with default Initializer." % name)


@register
class Xavier(Initializer):
    """Xavier/Glorot (initializer.py Xavier): scale by fan-in/out."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.
        if len(shape) < 2:
            raise ValueError("Xavier initializer cannot be applied to vector "
                             "%s. It requires at least 2D." % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape).astype(arr.dtype)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, shape).astype(arr.dtype)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He/MSRA init adjusted for PReLU (initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2. / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init via SVD of a random gaussian."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        res = self.scale * res.reshape(arr.shape)
        arr[:] = res.astype(arr.dtype)


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (deterministic; initializer.py Bilinear)."""

    def __init__(self):
        super().__init__()

    def _init_weight(self, _, arr):
        weight = np.zeros(np.prod(arr.shape), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.)
        c = (2 * f - 1 - f % 2) / (2. * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape).astype(arr.dtype)


@register
class LSTMBias(Initializer):
    """Set the forget-gate bias to a constant, others 0 (initializer.py)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=arr.dtype)
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b


@register
class FusedRNN(Initializer):
    """Initialize the packed parameter blob of a fused RNN cell by
    initializing each logical piece then packing (initializer.py FusedRNN)."""

    def __init__(self, init, num_hidden, num_layers, mode, bidirectional=False,
                 forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = _INIT_REGISTRY[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .rnn import rnn_cell
        cell = rnn_cell.FusedRNNCell(self._num_hidden, self._num_layers,
                                     self._mode, self._bidirectional,
                                     forget_bias=self._forget_bias,
                                     prefix="")
        args = cell.unpack_weights({cell._parameter.name: NDArray(arr.asnumpy())
                                    if not isinstance(arr, NDArray) else arr})
        for name in args:
            arg_desc = InitDesc(name, global_init=desc.global_init)
            # for lstm bias, we use a custom initializer which adds a bias to
            # the forget gate (reference FusedRNN._init_weight)
            if self._mode == "lstm" and name.endswith("_f_bias"):
                args[name][:] = self._forget_bias
            elif self._init is None:
                desc.global_init(arg_desc, args[name])
            else:
                self._init(arg_desc, args[name])
        arr[:] = cell.pack_weights(args)[cell._parameter.name].asnumpy()
