"""Row-sparse gradients out of the executor (VERDICT r4 missing #1).

Reference: FInferStorageType gradient dispatch
(include/mxnet/op_attr_types.h) + SparseEmbeddingOpBackwardRsp
(src/operator/tensor/indexing_op.cc:32-80) + dot backward storage
inference (src/operator/tensor/dot.cc:31).  Three executor paths:

  * 'rsp_probe' — dense-stored weight whose single consumer declares an
    O(nnz) sparse backward (Embedding sparse_grad=True; dot(csr, w)):
    the dense vjp for the weight is skipped, the op's sparse bwd runs on
    the consumer-output cotangent.
  * 'rsp_stored' — the arg itself is bound row-sparse; jax.vjp over its
    RSPValue pytree gives the O(nnz) cotangent directly.
  * the no-densify contract is asserted on the lowered StableHLO: with a
    vocab-sized extent that appears nowhere else, the compiled program
    must not contain it when the weight is rsp-stored.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def _rand_csr(rng, batch, dim, nnz_per_row):
    idx = np.stack([np.sort(rng.choice(dim, nnz_per_row, replace=False))
                    for _ in range(batch)]).astype(np.int64)
    val = rng.standard_normal((batch, nnz_per_row)).astype(np.float32)
    csr = mx.nd.sparse.csr_matrix(
        (val.reshape(-1), idx.reshape(-1),
         np.arange(0, batch * nnz_per_row + 1, nnz_per_row)),
        shape=(batch, dim))
    dense = np.zeros((batch, dim), np.float32)
    for i in range(batch):
        dense[i, idx[i]] = val[i]
    return csr, dense, np.unique(idx)


def test_dot_csr_emits_rsp_grad():
    """dot(csr, w) with dense-stored w: the w gradient comes back
    row-sparse with support = the csr's touched columns, matching the
    dense computation exactly."""
    rng = np.random.RandomState(0)
    B, D, N = 8, 64, 3
    csr, dense, touched = _rand_csr(rng, B, D, 4)
    w0 = rng.standard_normal((D, N)).astype(np.float32)

    data = mx.sym.Variable("data", stype="csr")
    w = mx.sym.Variable("w")
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.square(mx.sym.dot(data, w))))
    exe = net.bind(mx.cpu(), args={"data": csr, "w": mx.nd.array(w0)},
                   grad_req={"data": "null", "w": "write"})
    exe.forward(is_train=True)
    exe.backward()
    gw = exe.grad_dict["w"]
    assert gw.stype == "row_sparse"
    assert gw.data.shape[0] == B * 4          # csr nnz capacity
    expect = 2 * dense.T @ (dense @ w0)
    np.testing.assert_allclose(gw.tostype("default").asnumpy(), expect,
                               rtol=1e-4, atol=1e-5)
    # untouched rows are absent from the support
    got_rows = set(int(r) for r in gw.indices.asnumpy() if r >= 0)
    assert got_rows <= set(touched.tolist())


def test_embedding_sparse_grad():
    """Embedding(sparse_grad=True) with a dense-stored table: rsp grad
    with duplicate ids summed (AddTakeGradRspKernel semantics)."""
    rng = np.random.RandomState(1)
    V, E, B, T = 50, 6, 4, 7
    idx = rng.randint(0, V, (B, T)).astype(np.float32)
    idx[0, 0] = idx[0, 1] = 3          # force duplicates
    wt = rng.standard_normal((V, E)).astype(np.float32)

    d = mx.sym.Variable("data")
    w = mx.sym.Variable("weight")
    emb = mx.sym.Embedding(d, w, input_dim=V, output_dim=E,
                           sparse_grad=True)
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.square(emb)))
    exe = net.bind(mx.cpu(),
                   args={"data": mx.nd.array(idx), "weight": mx.nd.array(wt)},
                   grad_req={"data": "null", "weight": "write"})
    exe.forward(is_train=True)
    exe.backward()
    ge = exe.grad_dict["weight"]
    assert ge.stype == "row_sparse"
    assert ge.data.shape == (B * T, E)        # static nnz capacity
    expect = np.zeros((V, E), np.float32)
    for b in range(B):
        for t in range(T):
            expect[int(idx[b, t])] += 2 * wt[int(idx[b, t])]
    np.testing.assert_allclose(ge.tostype("default").asnumpy(), expect,
                               rtol=1e-4, atol=1e-5)


def test_embedding_dense_grad_unchanged():
    """sparse_grad=False keeps the dense gradient path."""
    rng = np.random.RandomState(2)
    V, E, B = 20, 4, 5
    idx = rng.randint(0, V, (B,)).astype(np.float32)
    wt = rng.standard_normal((V, E)).astype(np.float32)
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("weight")
    emb = mx.sym.Embedding(d, w, input_dim=V, output_dim=E)
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.square(emb)))
    exe = net.bind(mx.cpu(),
                   args={"data": mx.nd.array(idx), "weight": mx.nd.array(wt)},
                   grad_req={"data": "null", "weight": "write"})
    exe.forward(is_train=True)
    exe.backward()
    ge = exe.grad_dict["weight"]
    assert getattr(ge, "stype", "default") == "default"
    assert ge.shape == (V, E)


def test_rsp_stored_arg_grad():
    """A row-sparse-BOUND weight: only the stored rows live on device,
    and the gradient arrives as the RSPValue pytree cotangent."""
    rng = np.random.RandomState(3)
    B, D, N = 8, 64, 3
    csr, dense, touched = _rand_csr(rng, B, D, 4)
    w0 = rng.standard_normal((D, N)).astype(np.float32)
    wr = mx.nd.sparse.row_sparse_array((w0[touched], touched.copy()),
                                       shape=(D, N))

    data = mx.sym.Variable("data", stype="csr")
    w = mx.sym.Variable("w", stype="row_sparse")
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.square(mx.sym.dot(data, w))))
    exe = net.bind(mx.cpu(), args={"data": csr, "w": wr},
                   grad_req={"data": "null", "w": "write"})
    exe.forward(is_train=True)
    exe.backward()
    g = exe.grad_dict["w"]
    assert g.stype == "row_sparse"
    assert g.data.shape == (len(touched), N)   # the arg's own capacity
    wd = np.zeros((D, N), np.float32)
    wd[touched] = w0[touched]
    expect = 2 * dense.T @ (dense @ wd)
    got = g.tostype("default").asnumpy()
    np.testing.assert_allclose(got[touched], expect[touched],
                               rtol=1e-4, atol=1e-5)


def test_no_dense_vocab_materialization():
    """The no-densify contract, proven on the compiled program: with an
    rsp-stored weight of an unmistakable vocab extent, the lowered
    StableHLO of the fused fwd+bwd step must not mention that extent at
    all — no dense (vocab, dim) tensor exists on device in forward,
    backward, or the gradient outputs."""
    rng = np.random.RandomState(4)
    B, D, N = 8, 199481, 2            # prime-ish extent: greppable
    nnz = 4
    idx = np.stack([np.sort(rng.choice(D, nnz, replace=False))
                    for _ in range(B)]).astype(np.int64)
    val = rng.standard_normal((B, nnz)).astype(np.float32)
    csr = mx.nd.sparse.csr_matrix(
        (val.reshape(-1), idx.reshape(-1),
         np.arange(0, B * nnz + 1, nnz)), shape=(B, D))
    touched = np.unique(idx)
    wr = mx.nd.sparse.row_sparse_array(
        (rng.standard_normal((len(touched), N)).astype(np.float32),
         touched), shape=(D, N))

    data = mx.sym.Variable("data", stype="csr")
    w = mx.sym.Variable("w", stype="row_sparse")
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.square(mx.sym.dot(data, w))))
    exe = net.bind(mx.cpu(), args={"data": csr, "w": wr},
                   grad_req={"data": "null", "w": "write"})
    text = exe.lowered_fwd_bwd_text()
    assert "199481" not in text, \
        "a vocab-extent tensor appears in the compiled step"
    # and the step still runs + produces the rsp grad
    exe.forward(is_train=True)
    exe.backward()
    assert exe.grad_dict["w"].stype == "row_sparse"


def test_rsp_grad_req_add_rejected():
    rng = np.random.RandomState(5)
    csr, _, touched = _rand_csr(rng, 4, 32, 3)
    wr = mx.nd.sparse.row_sparse_array(
        (np.zeros((len(touched), 2), np.float32), touched), shape=(32, 2))
    data = mx.sym.Variable("data", stype="csr")
    w = mx.sym.Variable("w", stype="row_sparse")
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.dot(data, w)))
    with pytest.raises(MXNetError, match="add"):
        net.bind(mx.cpu(), args={"data": csr, "w": wr},
                 grad_req={"data": "null", "w": "add"})


def test_kvstore_push_dedups_duplicate_rows():
    """Padded duplicate rows in a pushed rsp gradient must be SUMMED
    before the lazy-update scatter (which is last-wins per row)."""
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.sparse.row_sparse_array(
        (np.zeros((0, 1), np.float32), np.zeros(0, np.int64)),
        shape=(8, 1)))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0,
                                         momentum=0.0, wd=0.0))
    g = mx.nd.sparse.row_sparse_array(
        (np.array([[1.0], [3.0], [5.0]], np.float32),
         np.array([2, 2, 6], np.int64)), shape=(8, 1))
    kv.push("w", g)
    out = mx.nd.zeros((8, 1))
    kv.pull("w", out=out)
    got = out.asnumpy()[:, 0]
    np.testing.assert_allclose(got[2], -4.0)   # 1+3 summed, not 3 last-wins
    np.testing.assert_allclose(got[6], -5.0)
    assert np.all(got[[0, 1, 3, 4, 5, 7]] == 0)


def test_kvstore_push_ignores_padding_rows():
    """Index -1 padding slots in an executor rsp gradient must not reach
    the update kernels, where -1 would wrap to the LAST row and apply a
    spurious wd/momentum update to it."""
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.sparse.row_sparse_array(
        (np.ones((8, 1), np.float32), np.arange(8, dtype=np.int64)),
        shape=(8, 1)))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0,
                                         momentum=0.0, wd=0.1))
    g = mx.nd.sparse.row_sparse_array(
        (np.array([[0.0], [2.0]], np.float32),
         np.array([-1, 3], np.int64)), shape=(8, 1))
    kv.push("w", g)
    out = mx.nd.zeros((8, 1))
    kv.pull("w", out=out)
    got = out.asnumpy()[:, 0]
    assert got[7] == 1.0, "padding row -1 corrupted the last row: %r" % got
    np.testing.assert_allclose(got[3], 1.0 - (2.0 + 0.1))


def test_user_dense_grad_buffer_respected():
    """A caller-supplied DENSE args_grad buffer keeps the dense vjp path
    (the bind contract): the buffer receives the gradient instead of
    being silently orphaned by probe classification."""
    rng = np.random.RandomState(6)
    B, D, N = 4, 24, 2
    csr, dense, _ = _rand_csr(rng, B, D, 3)
    w0 = rng.standard_normal((D, N)).astype(np.float32)
    gw = mx.nd.zeros((D, N))
    data = mx.sym.Variable("data", stype="csr")
    w = mx.sym.Variable("w")
    net = mx.sym.MakeLoss(mx.sym.sum(mx.sym.square(mx.sym.dot(data, w))))
    exe = net.bind(mx.cpu(), args={"data": csr, "w": mx.nd.array(w0)},
                   args_grad={"w": gw},
                   grad_req={"data": "null", "w": "write"})
    exe.forward(is_train=True)
    exe.backward()
    expect = 2 * dense.T @ (dense @ w0)
    np.testing.assert_allclose(gw.asnumpy(), expect, rtol=1e-4, atol=1e-5)


def test_sparse_end2end_example():
    """The flagship sparse workload trains O(nnz) end to end."""
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "sparse_end2end.py")
    spec = importlib.util.spec_from_file_location("sparse_end2end", path)
    modl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(modl)
    first, last = modl.main(["--num-batches", "8", "--epochs", "3"])
    assert last < first * 0.5, (first, last)
