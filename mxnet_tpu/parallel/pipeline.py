"""Pipeline parallelism: GPipe-style microbatched stage pipeline over the
'pp' mesh axis.

Absent in the reference (SURVEY §2.3: only PartialForward stepping exists,
include/mxnet/executor.h:70); built TPU-natively: every device holds one
stage's params; activations hop stage→stage with `ppermute` inside a
`lax.scan` over ticks, so the whole pipeline — bubbles and all — is one XLA
program.  With M microbatches and P stages the scan runs M+P-1 ticks.
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_shard_map", "pipeline_stage_fn",
           "pipeline_train_step", "PipelineModule"]


def pipeline_stage_fn(stage_fn, axis_name="pp"):
    """Wrap `stage_fn(params, x) -> y` into a per-device pipeline body to run
    inside shard_map: microbatches enter stage 0, exit stage P-1.

    Inputs inside shard_map (per device):
      params: this device's stage params (any pytree)
      x:      (M, mb, ...) all microbatches (only stage 0 reads them)
    Returns (M, mb, ...) outputs (only valid on the last stage; shard_map
    gathers the 'pp'-collected output of the last stage via psum masking).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(params, x):
        n_stage = lax.psum(1, axis_name)
        stage = lax.axis_index(axis_name)
        m = x.shape[0]
        n_ticks = m + n_stage - 1
        perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

        y0 = jnp.zeros_like(stage_fn(params, x[0]))
        outputs = jnp.zeros((m,) + y0.shape, y0.dtype)
        state = jnp.zeros_like(x[0])

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (if still in range)
            inject = x[jnp.minimum(t, m - 1)]
            state = jnp.where(stage == 0, inject, state)
            y = stage_fn(params, state)
            # last stage collects microbatch (t - n_stage + 1)
            out_idx = t - (n_stage - 1)
            valid = (stage == n_stage - 1) & (out_idx >= 0)
            outputs = lax.cond(
                valid,
                lambda o: o.at[jnp.maximum(out_idx, 0)].set(y),
                lambda o: o, outputs)
            # rotate activations to the next stage
            state = lax.ppermute(y, axis_name, perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(tick, (state, outputs),
                                       jnp.arange(n_ticks))
        # broadcast the last stage's collected outputs to every stage so the
        # shard_map out_spec can be replicated-over-pp
        outputs = lax.psum(
            jnp.where(stage == n_stage - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    return body


def pipeline_shard_map(stage_fn, mesh, stage_params, x, n_microbatch,
                       axis_name="pp"):
    """Run a full pipeline: split x into microbatches, stages over `mesh`.

    stage_params: pytree whose leaves have a leading stage axis of size P
    (device i gets slice i — its stage's params).
    x: (batch, ...) global input; batch must divide n_microbatch.
    Returns (batch, ...) outputs from the final stage.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    b = x.shape[0]
    assert b % n_microbatch == 0, \
        "n_microbatch must evenly divide the batch size"
    mb = b // n_microbatch
    xm = x.reshape((n_microbatch, mb) + x.shape[1:])

    body = pipeline_stage_fn(stage_fn, axis_name)
    param_spec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    fn = shard_map(
        lambda p, xx: body(jax.tree_util.tree_map(
            lambda l: l[0], p), xx),          # strip the stage axis
        mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P(),
        check_vma=False)
    out = fn(stage_params, xm)
    return out.reshape((b,) + out.shape[2:])


def pipeline_train_step(stage_fn, loss_fn, mesh, n_microbatch,
                        axis_name="pp", optimizer=None):
    """Build a jitted GPipe TRAINING step with full backward.

    The forward pipeline (scan over ticks + ppermute hops) is a pure
    differentiable function, so its `jax.grad` transpose IS the reverse
    pipeline schedule — microbatch cotangents flow stage P-1 → 0 through
    the transposed ppermutes, with the scan storing/rematerializing
    activations.  No hand-written backward schedule exists to get out of
    sync with the forward (the failure mode hand-rolled GPipe
    implementations have).

    stage_fn(params, x) -> y            one stage's forward
    loss_fn(y, labels) -> scalar        applied to final-stage outputs
    optimizer(p, g) -> p'               default: SGD(lr=0.01) leafwise

    Returns step(stage_params, x, labels) -> (loss, new_stage_params)
    where stage_params leaves carry a leading stage axis of size P.
    """
    import jax
    import jax.numpy as jnp

    if optimizer is None:
        def optimizer(p, g):
            return p - 0.01 * g

    def forward_loss(stage_params, x, labels):
        out = pipeline_shard_map(stage_fn, mesh, stage_params, x,
                                 n_microbatch, axis_name)
        return loss_fn(out, labels)

    @jax.jit
    def step(stage_params, x, labels):
        loss, grads = jax.value_and_grad(forward_loss)(stage_params, x,
                                                       labels)
        new_params = jax.tree_util.tree_map(optimizer, stage_params, grads)
        return loss, new_params

    return step


# ---------------------------------------------------------------------------
# Heterogeneous stages (embed -> body -> head)
# ---------------------------------------------------------------------------

def hetero_pipeline_train_step(stage_fns, stage_params, sample_x, loss_fn,
                               mesh, n_microbatch, axis_name="pp",
                               optimizer=None):
    """GPipe training step for stages with DIFFERENT params/activations
    (VERDICT r3 item #9; green field — the reference has no PP at all).

    The SPMD machinery needs one ppermute state shape and one stacked
    param array, so heterogeneity is packed away:
      * each stage's param pytree is raveled to a flat vector, zero-padded
        to the longest stage, and stacked -> (P, max_params), sharded
        P(axis) so device i holds (only) stage i's slice;
      * activations travel as per-sample flat buffers (mb, max_act); each
        stage unflattens its input shape, computes, re-flattens + pads;
      * `lax.switch` on the stage index picks the stage body inside the
        tick (every branch has the packed signature, so the switch is
        shape-uniform by construction).

    stage_fns:    [fn_j(params_j, x_j) -> y_j]  (per-stage pytrees/shapes)
    stage_params: [params_j pytree]             initial values
    sample_x:     ONE microbatch-shaped input (mb, ...) for stage 0 —
                  used to trace the inter-stage shapes
    loss_fn(y_last, labels) -> scalar
    Returns (step, pack, unpack): step(packed, x, labels) ->
    (loss, new_packed); pack/unpack convert [pytree] <-> the stacked flat
    array so callers can checkpoint real per-stage params.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.flatten_util import ravel_pytree
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    n_stage = len(stage_fns)
    assert mesh.shape[axis_name] == n_stage, \
        "mesh axis %r has %d devices but there are %d stages" \
        % (axis_name, mesh.shape[axis_name], n_stage)
    if optimizer is None:
        def optimizer(p, g):
            return p - 0.01 * g

    # --- param packing -------------------------------------------------
    flats, unravels = [], []
    for sp in stage_params:
        f, un = ravel_pytree(sp)
        flats.append(f)
        unravels.append(un)
    max_p = max(f.shape[0] for f in flats)

    def pack(params_list):
        rows = []
        for sp in params_list:
            f, _ = ravel_pytree(sp)
            rows.append(jnp.pad(f, (0, max_p - f.shape[0])))
        return jnp.stack(rows)

    def unpack(packed):
        return [unravels[j](packed[j, :flats[j].shape[0]])
                for j in range(n_stage)]

    # --- activation shapes: trace the chain once ------------------------
    in_shapes = [tuple(sample_x.shape)]
    x_spec = jax.ShapeDtypeStruct(sample_x.shape, jnp.float32)
    for j in range(n_stage):
        y_spec = jax.eval_shape(stage_fns[j], stage_params[j], x_spec)
        in_shapes.append(tuple(y_spec.shape))
        x_spec = y_spec
    out_shape = in_shapes[-1]
    mb = in_shapes[0][0]
    for s in in_shapes:
        assert s[0] == mb, "stages must preserve the microbatch dim"
    flat_sizes = [int(np.prod(s[1:])) for s in in_shapes]
    max_act = max(flat_sizes)

    def _stage_packed(j):
        def f(pflat, aflat):
            params = unravels[j](pflat[:flats[j].shape[0]])
            x = aflat[:, :flat_sizes[j]].reshape(in_shapes[j])
            y = stage_fns[j](params, x)
            yf = y.reshape(mb, -1)
            return jnp.pad(yf, ((0, 0), (0, max_act - yf.shape[1])))
        return f

    branches = [_stage_packed(j) for j in range(n_stage)]

    def body(pflat, xm):
        stage = lax.axis_index(axis_name)
        m = xm.shape[0]
        n_ticks = m + n_stage - 1
        perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
        outputs = jnp.zeros((m, mb, max_act), jnp.float32)
        state = jnp.zeros((mb, max_act), jnp.float32)

        def tick(carry, t):
            state, outputs = carry
            inject = xm[jnp.minimum(t, m - 1)]
            state = jnp.where(stage == 0, inject, state)
            y = lax.switch(stage, branches, pflat, state)
            out_idx = t - (n_stage - 1)
            valid = (stage == n_stage - 1) & (out_idx >= 0)
            outputs = lax.cond(
                valid,
                lambda o: o.at[jnp.maximum(out_idx, 0)].set(y),
                lambda o: o, outputs)
            state = lax.ppermute(y, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = lax.scan(tick, (state, outputs),
                                   jnp.arange(n_ticks))
        outputs = lax.psum(
            jnp.where(stage == n_stage - 1, outputs,
                      jnp.zeros_like(outputs)), axis_name)
        return outputs

    sm = shard_map(
        lambda p, xx: body(p[0], xx),     # strip the stage axis
        mesh=mesh, in_specs=(P(axis_name), P()), out_specs=P(),
        check_vma=False)

    def forward_loss(packed, x, labels):
        b = x.shape[0]
        assert b == n_microbatch * mb, \
            "batch %d != n_microbatch %d x microbatch %d" \
            % (b, n_microbatch, mb)
        m = n_microbatch
        xf = x.reshape(m, mb, -1)
        xm = jnp.pad(xf.astype(jnp.float32),
                     ((0, 0), (0, 0), (0, max_act - xf.shape[-1])))
        out = sm(packed, xm)                       # (m, mb, max_act)
        y = out[:, :, :flat_sizes[-1]].reshape((b,) + out_shape[1:])
        return loss_fn(y, labels)

    @jax.jit
    def step(packed, x, labels):
        loss, g = jax.value_and_grad(forward_loss)(packed, x, labels)
        return loss, optimizer(packed, g)

    return step, pack, unpack


class PipelineModule(object):
    """Module-style training driver for a homogeneous stage pipeline.

    Takes ONE stage symbol (input Variable 'data' -> output of the SAME
    shape, the scan-over-layers pattern used for transformer blocks) and
    replicates it across `n_stages` pipeline stages with per-stage
    parameters, plus a softmax cross-entropy head on the final stage.
    The bind/init_params/init_optimizer/forward_backward/update surface
    mirrors Module so training loops port over unchanged.

    Heterogeneous stages (different activation shapes per stage) are out
    of scope: the ppermute state has one shape by construction.
    """

    def __init__(self, stage_symbol, n_stages, n_microbatch, mesh=None,
                 axis_name="pp", logger=None):
        import jax
        import numpy as np
        from jax.sharding import Mesh
        self._sym = stage_symbol
        self._n_stages = n_stages
        self._n_micro = n_microbatch
        self._axis = axis_name
        if mesh is None:
            devs = np.array(jax.devices()[:n_stages])
            assert devs.size == n_stages, \
                "need %d devices for %d stages" % (n_stages, n_stages)
            mesh = Mesh(devs, (axis_name,))
        self._mesh = mesh
        self._step = None
        self._params = None
        self._arg_names = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **_ignored):
        from ..executor import build_graph_fn
        self._data_shape = tuple(data_shapes[0][1])
        self._arg_names = self._sym.list_arguments()
        self._aux_names = self._sym.list_auxiliary_states()
        assert not self._aux_names, \
            "PipelineModule stages must be aux-free (no BatchNorm stats)"
        self._graph_fn = build_graph_fn(self._sym, self._arg_names,
                                        self._aux_names)
        mb = self._data_shape[0] // self._n_micro
        shapes = {"data": (mb,) + self._data_shape[1:]}
        arg_shapes, out_shapes, _ = self._sym.infer_shape(**shapes)
        assert tuple(out_shapes[0]) == shapes["data"], \
            "stage output shape %s != input %s (homogeneous stages only)" \
            % (out_shapes[0], shapes["data"])
        self._param_shapes = {n: tuple(s) for n, s in
                              zip(self._arg_names, arg_shapes)
                              if n != "data"}
        self.binded = True

    def init_params(self, initializer=None, seed=0):
        import jax.numpy as jnp
        import numpy as np
        from ..initializer import Uniform
        from .. import ndarray as nd
        initializer = initializer or Uniform(0.07)
        from ..initializer import InitDesc
        params = {}
        for name, shape in self._param_shapes.items():
            stages = []
            for s in range(self._n_stages):
                arr = nd.zeros(shape)
                initializer(InitDesc("stage%d_%s" % (s, name)), arr)
                stages.append(arr.asnumpy())
            params[name] = jnp.asarray(np.stack(stages))
        self._params = params
        self.params_initialized = True

    def init_optimizer(self, learning_rate=0.01, **_ignored):
        import jax.numpy as jnp
        lr = learning_rate
        data_pos = self._arg_names.index("data")
        pnames = [n for n in self._arg_names if n != "data"]

        def stage_fn(params, x):
            args = []
            for n in self._arg_names:
                args.append(x if n == "data" else params[n])
            outs, _ = self._graph_fn(tuple(args), (), None, True)
            return outs[0]

        def loss_fn(out, labels):
            import jax
            logits = out.reshape(out.shape[0], -1)
            logp = jax.nn.log_softmax(logits)
            lab = labels.astype(jnp.int32)
            return -logp[jnp.arange(logits.shape[0]), lab].mean()

        self._train_step = pipeline_train_step(
            stage_fn, loss_fn, self._mesh, self._n_micro, self._axis,
            optimizer=lambda p, g: p - lr * g)
        self.optimizer_initialized = True
        self._loss = None

    def forward_backward(self, data_batch):
        import jax.numpy as jnp
        x = jnp.asarray(data_batch.data[0].asnumpy())
        y = jnp.asarray(data_batch.label[0].asnumpy())
        self._pending = (x, y)

    def update(self):
        x, y = self._pending
        self._loss, self._params = self._train_step(self._params, x, y)
        return self._loss

    @property
    def loss(self):
        import numpy as np
        return float(np.asarray(self._loss)) if self._loss is not None \
            else None

    def get_params(self):
        return self._params
