"""NDArray core tests (reference: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_create_and_convert():
    x = nd.array([[1, 2], [3, 4]])
    assert x.shape == (2, 2)
    assert x.dtype == np.float32
    assert np.array_equal(x.asnumpy(), [[1, 2], [3, 4]])
    y = nd.array(np.arange(6).reshape(2, 3), dtype="int32")
    assert y.dtype == np.int32
    assert x.context.device_type == "cpu"


def test_creation_helpers():
    assert (nd.zeros((2, 3)).asnumpy() == 0).all()
    assert (nd.ones((2, 3)).asnumpy() == 1).all()
    assert (nd.full((2,), 7).asnumpy() == 7).all()
    a = nd.arange(0, 10, 2)
    assert a.asnumpy().tolist() == [0, 2, 4, 6, 8]
    e = nd.empty((4, 5))
    assert e.shape == (4, 5)


def test_arith_dunders():
    x = nd.array([1., 2., 3.])
    y = nd.array([4., 5., 6.])
    assert (x + y).asnumpy().tolist() == [5., 7., 9.]
    assert (y - x).asnumpy().tolist() == [3., 3., 3.]
    assert (x * y).asnumpy().tolist() == [4., 10., 18.]
    assert np.allclose((y / x).asnumpy(), [4., 2.5, 2.])
    assert (x ** 2).asnumpy().tolist() == [1., 4., 9.]
    assert (2 ** x).asnumpy().tolist() == [2., 4., 8.]
    assert (1 - x).asnumpy().tolist() == [0., -1., -2.]
    assert (6 / x).asnumpy().tolist() == [6., 3., 2.]
    assert (-x).asnumpy().tolist() == [-1., -2., -3.]
    assert (x % 2).asnumpy().tolist() == [1., 0., 1.]
    assert abs(nd.array([-1., 2.])).asnumpy().tolist() == [1., 2.]


def test_comparisons():
    x = nd.array([1., 2., 3.])
    assert (x > 2).asnumpy().tolist() == [0., 0., 1.]
    assert (x == 2).asnumpy().tolist() == [0., 1., 0.]
    assert (x <= 2).asnumpy().tolist() == [1., 1., 0.]
    y = nd.array([3., 2., 1.])
    assert (x < y).asnumpy().tolist() == [1., 0., 0.]


def test_inplace_ops():
    b = nd.ones((3, 4))
    b += 2
    b *= 3
    assert (b.asnumpy() == 9).all()
    b /= 9
    assert (b.asnumpy() == 1).all()


def test_indexing():
    a = nd.arange(0, 12).reshape(3, 4)
    assert a[1].asnumpy().tolist() == [4., 5., 6., 7.]
    assert a[1:3].shape == (2, 4)
    assert float(a[2, 3].asscalar()) == 11.0
    a[1:3] = 0
    assert a.asnumpy()[1:].sum() == 0
    a[0, 1] = 99
    assert float(a[0, 1].asscalar()) == 99.0
    idx = nd.array([0, 2], dtype="int32")
    assert nd.take(a, idx).shape == (2, 4)


def test_reshape_magic():
    x = nd.ones((2, 3, 4))
    assert x.reshape(-1, 4).shape == (6, 4)
    assert x.reshape((0, -1)).shape == (2, 12)
    assert nd.Reshape(x, shape=(-3, 0)).shape == (6, 4)
    assert nd.Reshape(x, shape=(-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)
    assert nd.Flatten(x).shape == (2, 12)


def test_reductions():
    m = nd.array([[1., 2.], [3., 4.]])
    assert float(m.sum().asscalar()) == 10
    assert m.sum(1).asnumpy().tolist() == [3., 7.]
    assert m.sum(axis=0).asnumpy().tolist() == [4., 6.]
    assert m.mean(0).asnumpy().tolist() == [2., 3.]
    assert float(m.max().asscalar()) == 4
    assert float(nd.norm(m).asscalar()) == pytest.approx(np.sqrt(30))
    assert nd.argmax(m, axis=1).asnumpy().tolist() == [1., 1.]
    assert nd.sum(m, axis=1, keepdims=True).shape == (2, 1)


def test_broadcast():
    x = nd.array([[1.], [2.]])
    y = nd.array([[10., 20.]])
    assert (nd.broadcast_add(x, y)).asnumpy().tolist() == [[11., 21.], [12., 22.]]
    assert x.broadcast_to((2, 3)).shape == (2, 3)
    z = nd.ones((2,))
    assert z.broadcast_to((4, 2)).shape == (4, 2)


def test_concat_split_stack():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    assert nd.concatenate([a, b], axis=1).shape == (2, 6)


def test_dot():
    x = nd.array([[1., 2.], [3., 4.]])
    y = nd.array([[1., 1.], [1., 1.]])
    assert nd.dot(x, y).asnumpy().tolist() == [[3., 3.], [7., 7.]]
    assert nd.dot(x, y, transpose_b=True).asnumpy().tolist() == [[3., 3.], [7., 7.]]
    a = nd.ones((2, 3, 4))
    b = nd.ones((2, 4, 5))
    assert nd.batch_dot(a, b).shape == (2, 3, 5)


def test_astype_cast():
    x = nd.array([1.5, 2.5])
    assert x.astype("int32").dtype == np.int32
    assert nd.Cast(x, dtype="float16").dtype == np.float16


def test_save_load(tmp_path):
    f = str(tmp_path / "nd.params")
    d = {"a": nd.ones((2, 2)), "b": nd.arange(0, 4)}
    nd.save(f, d)
    back = nd.load(f)
    assert set(back) == {"a", "b"}
    assert np.array_equal(back["a"].asnumpy(), d["a"].asnumpy())
    lst = [nd.ones((2,)), nd.zeros((3,))]
    nd.save(f, lst)
    back = nd.load(f)
    assert isinstance(back, list) and len(back) == 2


def test_copy_context():
    x = nd.ones((2, 2))
    y = x.copy()
    y += 1
    assert (x.asnumpy() == 1).all()
    z = x.as_in_context(mx.cpu(0))
    assert z.context.device_type == "cpu"
    w = nd.zeros((2, 2))
    x.copyto(w)
    assert (w.asnumpy() == 1).all()


def test_multi_device_cpu():
    """Multi-device semantics on virtual CPU devices (the reference's
    test_multi_device_exec.py pattern)."""
    a = nd.ones((2, 2), ctx=mx.cpu(0))
    b = nd.ones((2, 2), ctx=mx.cpu(1))
    assert a.context == mx.cpu(0)
    assert b.context == mx.cpu(1)
    c = b.as_in_context(mx.cpu(0)) + a
    assert c.context == mx.cpu(0)
    assert (c.asnumpy() == 2).all()


def test_out_kwarg():
    x = nd.array([1., 2.])
    o = nd.zeros((2,))
    nd.elemwise_add(x, x, out=o)
    assert o.asnumpy().tolist() == [2., 4.]


def test_scalar_helpers():
    x = nd.array([1., 2., 3.])
    assert nd.maximum(x, 2).asnumpy().tolist() == [2., 2., 3.]
    assert nd.minimum(x, 2).asnumpy().tolist() == [1., 2., 2.]
    assert nd.power(x, nd.array([2., 2., 2.])).asnumpy().tolist() == [1., 4., 9.]


def test_unary_math():
    x = nd.array([0.5, 1.0])
    assert np.allclose(nd.exp(x).asnumpy(), np.exp([0.5, 1.0]), rtol=1e-5)
    assert np.allclose(nd.log(x).asnumpy(), np.log([0.5, 1.0]), rtol=1e-5)
    assert np.allclose(nd.sigmoid(x).asnumpy(), 1 / (1 + np.exp([-0.5, -1.0])), rtol=1e-5)
    assert np.allclose(nd.gamma(nd.array([-0.5, 0.5, 3.0])).asnumpy(),
                       [-3.5449077, 1.7724539, 2.0], atol=1e-4)
    assert nd.relu(nd.array([-1., 1.])).asnumpy().tolist() == [0., 1.]


def test_ordering():
    x = nd.array([[3., 1., 2.]])
    assert nd.sort(x).asnumpy().tolist() == [[1., 2., 3.]]
    assert nd.argsort(x).asnumpy().tolist() == [[1., 2., 0.]]
    assert nd.topk(x, k=2, ret_typ="value").asnumpy().tolist() == [[3., 2.]]


def test_one_hot_embedding():
    idx = nd.array([0, 2])
    oh = nd.one_hot(idx, depth=3)
    assert oh.asnumpy().tolist() == [[1., 0., 0.], [0., 0., 1.]]
    w = nd.array(np.arange(12).reshape(4, 3))
    e = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    assert e.asnumpy().tolist() == [[0., 1., 2.], [6., 7., 8.]]
