"""Predictor (inference-only runtime) tests.

Reference: include/mxnet/c_predict_api.h contract — build from checkpoint
artifacts, set input, forward, get output; partial outputs; reshape.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _train_and_checkpoint(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.standard_normal((32, 6)).astype(np.float32)
    W = rng.standard_normal((3, 6)).astype(np.float32)
    Y = (X @ W.T).argmax(1).astype(np.float32)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    import logging
    logging.disable(logging.CRITICAL)
    mod.fit(it, num_epoch=20, optimizer_params={"learning_rate": 0.2},
            epoch_end_callback=mx.callback.do_checkpoint(
                str(tmp_path / "m")))
    acc = mx.metric.Accuracy()
    mod.score(it, acc)
    return X, Y, acc.get()[1]


def test_predictor_from_checkpoint(tmp_path):
    X, Y, train_acc = _train_and_checkpoint(tmp_path)
    assert train_acc > 0.8
    pred = mx.predict.load_checkpoint_predictor(
        str(tmp_path / "m"), 20, {"data": (8, 6)}, ctx=mx.cpu())
    correct = 0
    for i in range(0, 32, 8):
        out = pred.forward(data=X[i:i + 8]).get_output(0)
        correct += (out.argmax(1) == Y[i:i + 8]).sum()
    assert correct / 32 >= train_acc - 1e-6  # same predictions as Module


def test_predictor_partial_out(tmp_path):
    _train_and_checkpoint(tmp_path)
    pred = mx.predict.load_checkpoint_predictor(
        str(tmp_path / "m"), 20, {"data": (4, 6)}, ctx=mx.cpu(),
        output_names=["relu1_output"])
    out = pred.forward(data=np.zeros((4, 6), np.float32)).get_output(0)
    assert out.shape == (4, 16)


def test_predictor_reshape(tmp_path):
    X, _, _ = _train_and_checkpoint(tmp_path)
    pred = mx.predict.load_checkpoint_predictor(
        str(tmp_path / "m"), 20, {"data": (8, 6)}, ctx=mx.cpu())
    big = pred.reshape({"data": (16, 6)})
    out = big.forward(data=X[:16]).get_output(0)
    assert out.shape == (16, 3)
    ref = pred.forward(data=X[:8]).get_output(0)
    np.testing.assert_allclose(out[:8], ref, rtol=1e-5, atol=1e-6)


def test_predictor_reshape_no_param_reupload(tmp_path):
    """Regression: reshape must reuse the device-resident params of the
    bound executor — the SAME NDArray objects backed by the SAME jax
    buffers, with no host→device re-upload and no as_in_context walk
    (predict.py reshape fast path)."""
    X, _, _ = _train_and_checkpoint(tmp_path)
    pred = mx.predict.load_checkpoint_predictor(
        str(tmp_path / "m"), 20, {"data": (8, 6)}, ctx=mx.cpu())
    import jax
    puts = []
    orig_put = jax.device_put

    def counting_put(x, *a, **kw):
        puts.append(getattr(x, "shape", None))
        return orig_put(x, *a, **kw)

    jax.device_put = counting_put
    try:
        big = pred.reshape({"data": (16, 6)})
    finally:
        jax.device_put = orig_put
    param_names = [n for n in pred._sym.list_arguments()
                   if n != "data" and not (n.endswith("_label")
                                           or n == "label")]
    assert param_names
    for n in param_names:
        # shared object AND shared device buffer: nothing was copied
        assert big._exec.arg_dict[n] is pred._exec.arg_dict[n]
        assert big._exec.arg_dict[n]._data is pred._exec.arg_dict[n]._data
    for n, arr in pred._exec.aux_dict.items():
        assert big._exec.aux_dict[n]._data is arr._data
    # no param-sized host array crossed to the device during reshape
    param_shapes = {tuple(pred._exec.arg_dict[n].shape)
                    for n in param_names}
    assert not [s for s in puts if s in param_shapes]
    # and the reshaped predictor still computes the same function
    out = big.forward(data=X[:16]).get_output(0)
    ref = pred.forward(data=X[:8]).get_output(0)
    np.testing.assert_allclose(out[:8], ref, rtol=1e-5, atol=1e-6)


def test_predictor_get_outputs(tmp_path):
    X, _, _ = _train_and_checkpoint(tmp_path)
    pred = mx.predict.load_checkpoint_predictor(
        str(tmp_path / "m"), 20, {"data": (4, 6)}, ctx=mx.cpu(),
        output_names=["relu1_output", "softmax_output"])
    with pytest.raises(mx.MXNetError):
        pred.get_outputs()                       # before forward
    pred.forward(data=X[:4])
    outs = pred.get_outputs()
    assert isinstance(outs, list) and len(outs) == 2
    np.testing.assert_array_equal(outs[0], pred.get_output(0))
    np.testing.assert_array_equal(outs[1], pred.get_output(1))
    # as_numpy=False hands back the device-resident NDArrays themselves
    dev = pred.get_outputs(as_numpy=False)
    assert all(d is o for d, o in zip(dev, pred._outputs))
    np.testing.assert_array_equal(dev[1].asnumpy(), outs[1])
