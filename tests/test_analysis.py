"""Static-analysis suite tests (mxnet_tpu/analysis + tools/graph_lint.py).

No reference analog — the reference discovers graph problems at
bind/dispatch time.  Coverage per the subsystem contract: each pass
family (verifier, shape/dtype abstract interpretation, retrace-hazard,
padding-soundness) must catch a seeded defect with a node-level
provenance message, clean graphs must lint clean, and the CLI --strict
exit codes must hold.
"""
import json
import subprocess
import sys
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.analysis import Severity
from mxnet_tpu.serving import BucketPolicy
from mxnet_tpu.symbol.symbol import SymNode, Symbol

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _findings(report, pass_name, severity=None):
    out = report.by_pass(pass_name)
    if severity is not None:
        out = [d for d in out if d.severity == severity]
    return out


# ---------------------------------------------------------------------------
# verifier
# ---------------------------------------------------------------------------

def test_verifier_clean_graph():
    report = analysis.verify(_mlp())
    assert report.ok and not report.warnings


def test_verifier_catches_cycle():
    net = _mlp()
    # seed a cycle: fc1's data input becomes the softmax head itself
    head = net._outputs[0][0]
    topo = [n for n in analysis.GraphView(net).topo]
    fc1 = next(n for n in topo if n.name == "fc1")
    fc1.inputs[0] = (head, 0)
    report = analysis.verify(net)
    errs = _findings(report, "verify", Severity.ERROR)
    assert errs and "cycle" in errs[0].message
    assert "fc1" in errs[0].message and "softmax" in errs[0].message
    # structural failure gates the rest of the pipeline
    full, ctx = analysis.analyze(net, data_shapes={"data": (2, 4)})
    assert ctx.structural_ok is False
    assert not full.by_pass("shapes")


def test_verifier_catches_duplicate_argument_name():
    a = mx.sym.Variable("x")
    b = mx.sym.Variable("x")        # distinct node, same name
    net = a + b
    report = analysis.verify(net)
    errs = _findings(report, "verify", Severity.ERROR)
    assert errs and "duplicate argument name 'x'" in errs[0].message


def test_verifier_catches_dangling_output_reference():
    data = mx.sym.Variable("data")
    net = mx.sym.Activation(data, act_type="relu", name="act")
    node = net._outputs[0][0]
    node.inputs[0] = (node.inputs[0][0], 3)     # var has 1 output
    report = analysis.verify(net)
    errs = _findings(report, "verify", Severity.ERROR)
    assert errs and "dangling" in errs[0].message
    assert errs[0].node == "act" and errs[0].op == "Activation"


def test_verifier_catches_arity_mismatch():
    data = mx.sym.Variable("data")
    net = mx.sym.Activation(data, act_type="relu", name="act")
    node = net._outputs[0][0]
    node.inputs.append((mx.sym.Variable("extra")._outputs[0][0], 0))
    report = analysis.verify(net)
    errs = _findings(report, "verify", Severity.ERROR)
    assert any("arity mismatch" in e.message for e in errs)


def test_verifier_catches_attr_schema_violation():
    data = mx.sym.Variable("data")
    net = mx.sym.Activation(data, act_type="relu", name="act")
    node = net._outputs[0][0]
    node.attrs["act_type"] = "warp_drive"       # not a valid choice
    report = analysis.verify(net)
    errs = _findings(report, "verify", Severity.ERROR)
    assert errs and "attr schema" in errs[0].message
    assert errs[0].node == "act"


def test_verifier_catches_unregistered_op():
    from mxnet_tpu.ops.registry import OpDef
    rogue = OpDef("not_a_real_op", lambda attrs, x: x)
    node = SymNode(rogue, "rogue0", {},
                   [(mx.sym.Variable("data")._outputs[0][0], 0)])
    report = analysis.verify(Symbol([(node, 0)]))
    errs = _findings(report, "verify", Severity.ERROR)
    assert errs and "not in the registry" in errs[0].message


# ---------------------------------------------------------------------------
# shape/dtype abstract interpretation
# ---------------------------------------------------------------------------

def test_shape_pass_provenance_on_rank_mismatch():
    """The ISSUE exemplar: a conv feeding an op that rejects its rank —
    the diagnostic must name the failing node, show the concrete input
    shapes, and carry the dataflow path."""
    x = mx.sym.Variable("data")
    c = mx.sym.Convolution(x, kernel=(3, 3), num_filter=4, name="conv0")
    f = mx.sym.FullyConnected(c, num_hidden=10, name="fc1")
    bad = mx.sym.dot(f, f, name="bad_dot")      # (8,10)x(8,10): mismatch
    report, _ = analysis.analyze(bad,
                                 data_shapes={"data": (8, 3, 24, 24)})
    errs = _findings(report, "shapes", Severity.ERROR)
    assert len(errs) == 1
    d = errs[0]
    assert d.node == "bad_dot" and d.op == "dot"
    assert "lhs=(8, 10)" in d.message           # concrete shapes shown
    assert d.provenance[0] == "data" and "conv0" in d.provenance


def test_shape_pass_clean_and_fills_context():
    report, ctx = analysis.analyze(_mlp(), data_shapes={"data": (4, 6)})
    assert report.ok
    head = ctx.view.heads[0]
    assert ctx.shapes[(id(head[0]), 0)] == (4, 3)


def test_shape_pass_reports_first_blocked_node():
    net = _mlp()
    report, _ = analysis.analyze(net, data_shapes={})   # nothing known
    blocked = _findings(report, "shapes", Severity.WARNING)
    assert blocked and blocked[0].node == "fc1"
    assert "data" in blocked[0].message


def test_infer_shape_error_names_blocked_node():
    """Satellite: Symbol.infer_shape itself now says WHICH node the
    fixed point stalled on, not only the missing-args list."""
    net = _mlp()
    with pytest.raises(mx.MXNetError) as ei:
        net.infer_shape()                       # no shapes at all
    msg = str(ei.value)
    assert "'fc1'" in msg and "FullyConnected" in msg
    assert "data" in msg


def test_shape_pass_dynamic_dim_abstraction_notes():
    report, _ = analysis.analyze(_mlp(), data_shapes={"data": (0, 6)})
    infos = [d for d in report.by_pass("shapes")
             if d.severity == Severity.INFO]
    assert any("abstracted" in d.message for d in infos)


# ---------------------------------------------------------------------------
# retrace-hazard linter + host-sync detector
# ---------------------------------------------------------------------------

def test_retrace_flags_unbucketed_dynamic_dim():
    """A non-pow2 dynamic dim with no bucket policy = one compile per
    distinct size under live traffic."""
    report, _ = analysis.analyze(_mlp(), data_shapes={"data": (0, 6)})
    warns = _findings(report, "retrace", Severity.WARNING)
    assert warns and warns[0].node == "data"
    assert "new XLA program" in warns[0].message


def test_retrace_dynamic_dim_covered_by_buckets_is_quiet():
    policy = BucketPolicy(max_batch=4, seq_axis=0, seq_buckets=(4, 8))
    net = mx.sym.Activation(mx.sym.Variable("data"), act_type="tanh")
    report, _ = analysis.analyze(net, data_shapes={"data": (2, 0, 3)},
                                 policy=policy)
    assert not _findings(report, "retrace", Severity.WARNING)
    infos = _findings(report, "retrace", Severity.INFO)
    assert any("program" in d.message for d in infos)


def test_retrace_flags_shape_literal_downstream_of_dynamic_dim():
    data = mx.sym.Variable("data")
    net = mx.sym.Reshape(data, shape=(4, 6), name="rigid")
    report, _ = analysis.analyze(net, data_shapes={"data": (0, 24)})
    warns = _findings(report, "retrace", Severity.WARNING)
    assert any(d.node == "rigid" and "shape-literal" in d.message
               for d in warns)
    # wildcard reshape stays quiet
    net2 = mx.sym.Reshape(data, shape=(-1, 6), name="poly")
    report2, _ = analysis.analyze(net2, data_shapes={"data": (0, 24)})
    assert not any(d.node == "poly" for d in
                   _findings(report2, "retrace", Severity.WARNING))


def test_retrace_flags_jit_cache_busting_attr():
    net = mx.sym.Activation(mx.sym.Variable("data"), act_type="relu",
                            name="act")
    net._outputs[0][0].attrs["lookup"] = np.zeros((3,))
    report, _ = analysis.analyze(net, data_shapes={"data": (2, 3)},
                                 passes=("verify", "retrace"))
    warns = _findings(report, "retrace", Severity.WARNING)
    assert any("jit cache" in d.message and d.node == "act"
               for d in warns)


def test_host_sync_detector_flags_custom_op():
    import mxnet_tpu.operator as op_mod

    class Prop(op_mod.CustomOpProp):
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Op(op_mod.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0].asnumpy())
            return Op()

    op_mod.register("lint_probe_op")(Prop)
    net = mx.sym.Custom(mx.sym.Variable("data"), op_type="lint_probe_op",
                        name="hostcall")
    report, _ = analysis.analyze(net, data_shapes={"data": (2, 3)},
                                 passes=("verify", "retrace"))
    warns = _findings(report, "retrace", Severity.WARNING)
    assert any("host" in d.message.lower() and d.node == "hostcall"
               for d in warns)


# ---------------------------------------------------------------------------
# padding-soundness
# ---------------------------------------------------------------------------

def test_padding_row_local_mlp():
    verdicts, report = analysis.check_serving_graph(
        _mlp(), {"data": (6,)}, BucketPolicy(max_batch=4))
    assert verdicts == {"batch": "row-local"}
    assert not report.warnings


def test_padding_cross_position_softmax_over_batch():
    net = mx.sym.softmax(mx.sym.Variable("data"), axis=0, name="sm0")
    verdicts, report = analysis.check_serving_graph(
        net, {"data": (6,)}, BucketPolicy(max_batch=4))
    assert verdicts["batch"] == "cross-position"
    warns = [d for d in report.warnings if d.node == "sm0"]
    assert warns and "softmax" in warns[0].message
    assert warns[0].provenance == ("data", "sm0")


def test_padding_seq_axis_sum_absorbs_but_mean_mixes():
    """Zero pads are absorbing for sum (exact — the engine's existing
    unpad test relies on it) but not for mean."""
    data = mx.sym.Variable("data")
    policy = BucketPolicy(max_batch=2, seq_axis=0, seq_buckets=(4,))
    ok = mx.sym.Group([mx.sym.sum(data, axis=1, name="pool"),
                       mx.sym.Activation(data, act_type="tanh")])
    verdicts, _ = analysis.check_serving_graph(ok, {"data": (4, 3)},
                                               policy)
    assert verdicts["seq"] == "row-local"
    bad = mx.sym.mean(data, axis=1, name="avg")
    verdicts, report = analysis.check_serving_graph(bad, {"data": (4, 3)},
                                                    policy)
    assert verdicts["seq"] == "cross-position"
    assert any(d.node == "avg" for d in report.warnings)


def test_padding_zero_chain_tracking():
    """sigmoid(0) != 0, so a sum over the padded axis AFTER a sigmoid is
    no longer absorbing — the zero bit must degrade along the chain."""
    data = mx.sym.Variable("data")
    policy = BucketPolicy(max_batch=2, seq_axis=0, seq_buckets=(4,))
    net = mx.sym.sum(mx.sym.Activation(data, act_type="sigmoid"),
                     axis=1, name="pool")
    verdicts, report = analysis.check_serving_graph(net, {"data": (4, 3)},
                                                    policy)
    assert verdicts["seq"] == "cross-position"
    assert any(d.node == "pool" and "no longer zero" in d.message
               for d in report.warnings)
    # relu keeps zeros -> still exact
    net2 = mx.sym.sum(mx.sym.Activation(data, act_type="relu"),
                      axis=1, name="pool")
    verdicts2, _ = analysis.check_serving_graph(net2, {"data": (4, 3)},
                                                policy)
    assert verdicts2["seq"] == "row-local"


def test_padding_unknown_op_is_conservative():
    from mxnet_tpu.ops.registry import register

    @register("_lint_mystery_op")
    def _mystery(attrs, x):
        return x

    from mxnet_tpu.symbol.symbol import _create
    net = _create("_lint_mystery_op", [mx.sym.Variable("data")],
                  {}, name="mystery")
    verdicts, report = analysis.check_serving_graph(
        net, {"data": (6,)}, BucketPolicy(max_batch=2))
    assert verdicts["batch"] == "cross-position"
    assert any("no padding-soundness rule" in d.message
               for d in report.warnings)


def test_padding_training_batchnorm_mixes():
    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(data, name="bn0")
    policy = BucketPolicy(max_batch=4)
    # inference: moving stats, row-local
    v_inf, _ = analysis.check_serving_graph(net, {"data": (3, 5, 5)},
                                            policy)
    assert v_inf["batch"] == "row-local"
    # training: batch statistics fold pad rows into every output
    v_tr, report = analysis.check_serving_graph(
        net, {"data": (3, 5, 5)}, policy, training=True)
    assert v_tr["batch"] == "cross-position"
    assert any(d.node == "bn0" for d in report.warnings)


def test_padding_reorder_along_padded_axis():
    net = mx.sym.reverse(mx.sym.Variable("data"), axis=(0,), name="flip")
    verdicts, report = analysis.check_serving_graph(
        net, {"data": (6,)}, BucketPolicy(max_batch=4))
    assert verdicts["batch"] == "cross-position"
    assert any(d.node == "flip" and "reorder" in d.message
               for d in report.warnings)


def test_model_zoo_exemplars_row_local():
    from mxnet_tpu.models.lenet import get_lenet
    from mxnet_tpu.models.resnet import get_resnet_symbol
    pol = BucketPolicy(max_batch=4)
    for net, shp in [(get_lenet(), (1, 28, 28)),
                     (get_resnet_symbol(num_classes=10, num_layers=18,
                                        image_shape=(3, 32, 32)),
                      (3, 32, 32))]:
        verdicts, report = analysis.check_serving_graph(
            net, {"data": shp}, pol)
        assert report.clean(strict=True), report.format()
        assert verdicts["batch"] == "row-local"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _run_lint(args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graph_lint.py")]
        + args, capture_output=True, text=True, env=env, cwd=REPO)


def test_cli_strict_clean_graph_exits_zero(tmp_path):
    path = str(tmp_path / "mlp-symbol.json")
    _mlp().save(path)
    r = _run_lint([path, "--shapes", "data=8,6", "--strict"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "row-local" in r.stdout


def test_cli_strict_flags_defect_nonzero(tmp_path):
    net = mx.sym.softmax(mx.sym.Variable("data"), axis=0, name="sm0")
    path = str(tmp_path / "bad-symbol.json")
    net.save(path)
    r = _run_lint([path, "--shapes", "data=8,6", "--strict"])
    assert r.returncode == 1
    assert "sm0" in r.stdout and "cross-position" in r.stdout
    # non-strict: warnings alone do not fail the run
    r2 = _run_lint([path, "--shapes", "data=8,6"])
    assert r2.returncode == 0


def test_cli_unknown_graph_exits_two():
    r = _run_lint(["no_such_model_or_file"])
    assert r.returncode == 2


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------

def test_padding_greater_scalar_zero_rule_sign_sensitive():
    """(pad=0) > c is 1 for negative c: the zero bit must NOT survive a
    negative-threshold comparison, or a downstream sum over the padded
    axis absorbs spurious ones (regression: the rule was coded
    unconditionally True)."""
    data = mx.sym.Variable("data")
    policy = BucketPolicy(max_batch=2, seq_axis=0, seq_buckets=(4,))
    bad = mx.sym.sum(data > -1.0, axis=1, name="pool")
    verdicts, report = analysis.check_serving_graph(bad, {"data": (4, 3)},
                                                    policy)
    assert verdicts["seq"] == "cross-position"
    # non-negative threshold keeps 0 > c == 0: still absorbing
    ok = mx.sym.sum(data > 0.5, axis=1, name="pool")
    verdicts2, _ = analysis.check_serving_graph(ok, {"data": (4, 3)},
                                                policy)
    assert verdicts2["seq"] == "row-local"


def test_padding_sequence_mask_value_controls_zero_bit():
    """SequenceMask(value=0) restores the zero invariant on its axis
    (sum-over-pads exact again); any other value destroys it."""
    data = mx.sym.Variable("data")
    slen = mx.sym.Variable("slen")
    shapes = {"data": (2, 8, 3), "slen": (2,)}
    spec = {"seq": {"data": 1}}
    for value, want in [(0.0, "row-local"), (5.0, "cross-position")]:
        m = mx.sym.SequenceMask(data, slen, use_sequence_length=True,
                                value=value, axis=1, name="mask")
        net = mx.sym.sum(m, axis=1, name="pool")
        verdicts, _ = analysis.classify_padding(net, shapes, spec)
        assert verdicts["seq"] == want, (value, verdicts)


def test_padding_batch_dot_is_row_local_over_batch_axis():
    """Attention-style batch_dot must NOT be mistaken for a contraction
    of the batch axis (that misclassification would silently disable
    request coalescing for every attention model)."""
    q, k = mx.sym.Variable("q"), mx.sym.Variable("k")
    att = mx.sym.batch_dot(q + 1.0, k + 1.0, name="scores")
    shapes = {"q": (4, 5, 6), "k": (4, 6, 5)}
    verdicts, report = analysis.classify_padding(
        att, shapes, {"batch": {"q": 0, "k": 0}})
    assert verdicts["batch"] == "row-local", report.format()
    # contracting a padded (nonzero) axis still flags
    verdicts2, _ = analysis.classify_padding(
        att, shapes, {"seq": {"q": 2, "k": 1}})
    assert verdicts2["seq"] == "cross-position"


def test_padding_pass_alone_pulls_in_shape_environment():
    """`--passes padding` (the invocation the runtime probe's error
    message recommends) must resolve negative softmax axes — the shapes
    pass is auto-inserted as its dependency."""
    net = mx.sym.softmax(mx.sym.Variable("data"), axis=-1, name="sm")
    _, ctx = analysis.analyze(net, data_shapes={"data": (4, 6)},
                              pad_axes={"seq": {"data": 1}},
                              passes=("verify", "padding"))
    assert ctx.pad_verdicts["seq"] == "cross-position"


def test_retrace_adjacent_dynamic_dim_not_masked_by_seq_coverage():
    """A dynamic dim NEXT TO the bucketed seq axis must still warn
    (coverage is exact, not seq_axis +/- 1)."""
    policy = BucketPolicy(max_batch=2, seq_axis=0, seq_buckets=(8,))
    net = mx.sym.Activation(mx.sym.Variable("data"), act_type="tanh")
    report, _ = analysis.analyze(net, data_shapes={"data": (0, 0, 0)},
                                 policy=policy)
    warns = _findings(report, "retrace", Severity.WARNING)
    assert len(warns) == 1 and "dim 2" in warns[0].message
    # batch axis 0 and seq graph-axis 1 are covered by the grid
    assert not any("dim 0" in d.message or "dim 1" in d.message
                   for d in warns)


def test_crashed_pass_degrades_to_warning(monkeypatch):
    """An analyzer bug must never brick strict-mode construction of a
    valid graph: crashes surface as warnings (CI --strict still fails),
    not errors."""
    from mxnet_tpu.analysis import ShapeDtypePass

    def boom(self, ctx, report):
        raise RuntimeError("kaput")

    monkeypatch.setattr(ShapeDtypePass, "run", boom)
    report, _ = analysis.analyze(_mlp(), data_shapes={"data": (2, 6)},
                                 passes=("verify", "shapes"))
    assert report.ok
    assert any("crashed" in d.message for d in report.warnings)


def test_cli_shape_parse_trailing_comma(tmp_path):
    path = str(tmp_path / "mlp-symbol.json")
    _mlp().save(path)
    r = _run_lint([path, "--shapes", "data=8,6,", "--strict"])
    assert r.returncode == 0, r.stdout + r.stderr
