"""Per-shape conv probe: native XLA conv vs dot_general reformulation.

WARNING: through the axon dev tunnel this probe's absolute timings are
GARBAGE — repeated identical executable calls are served from a cache
(PROFILE_r04.md, "Wall-clock microbenchmarks ... are invalid"), and the
calls here are intentionally unchained.  On a directly-attached TPU the
numbers are real.  Through the tunnel, use perf/step_bench.py (whole-step,
donated params chaining) or xplane traces instead.

For each distinct (fwd / dgrad / wgrad) conv in ResNet-50 (batch 256, NHWC,
bf16) this times the lax.conv_general_dilated form XLA autodiff produces
against an explicit MXU-matmul reformulation:

  * 1x1 stride-1 conv  == matmul over (N*H*W, Cin) x (Cin, Cout)
  * 1x1 stride-s fwd   == subsample then matmul
  * 1x1 stride-s dgrad == matmul then interior-dilate (lax.pad)
  * 1x1 stride-s wgrad == subsample x then matmul
  * 3x3 wgrad          == optional im2col matmul (bandwidth-heavy; measured)

Timing: marginal K2-K1 chained-dispatch protocol (same as bench.py) so the
fixed tunnel sync cost cancels.  Prints a table + JSON lines.

Usage: python perf/conv_probe.py [--quick]
"""
import argparse
import functools
import json
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

DT = jnp.bfloat16

# (name, H, Cin, Cout, K, stride)  -- batch fixed at 256, square spatial/kernel
RESNET50_CONVS = [
    ("stem7x7",    224,    3,   64, 7, 2),
    ("s1_in1x1",    56,  256,   64, 1, 1),
    ("s1_3x3",      56,   64,   64, 3, 1),
    ("s1_out1x1",   56,   64,  256, 1, 1),
    ("s2_in1x1",    56,  256,  128, 1, 1),
    ("s2_3x3s2",    56,  128,  128, 3, 2),
    ("s2_proj",     56,  256,  512, 1, 2),
    ("s2_in1x1b",   28,  512,  128, 1, 1),
    ("s2_3x3",      28,  128,  128, 3, 1),
    ("s2_out1x1",   28,  128,  512, 1, 1),
    ("s3_in1x1",    28,  512,  256, 1, 1),
    ("s3_3x3s2",    28,  256,  256, 3, 2),
    ("s3_proj",     28,  512, 1024, 1, 2),
    ("s3_in1x1b",   14, 1024,  256, 1, 1),
    ("s3_3x3",      14,  256,  256, 3, 1),
    ("s3_out1x1",   14,  256, 1024, 1, 1),
    ("s4_in1x1",    14, 1024,  512, 1, 1),
    ("s4_3x3s2",    14,  512,  512, 3, 2),
    ("s4_proj",     14, 1024, 2048, 1, 2),
    ("s4_in1x1b",    7, 2048,  512, 1, 1),
    ("s4_3x3",       7,  512,  512, 3, 1),
    ("s4_out1x1",    7,  512, 2048, 1, 1),
]

QUICK = [
    ("s4_in1x1b",    7, 2048,  512, 1, 1),
    ("s4_out1x1",    7,  512, 2048, 1, 1),
    ("s3_in1x1b",   14, 1024,  256, 1, 1),
    ("s3_out1x1",   14,  256, 1024, 1, 1),
]

DN = ("NHWC", "OHWI", "NHWC")


def native_fwd(x, w, stride, pad):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=[(pad, pad)] * 2,
        dimension_numbers=DN, preferred_element_type=x.dtype)


def native_dgrad(x, w, dy, stride, pad):
    _, vjp = jax.vjp(lambda x_: native_fwd(x_, w, stride, pad), x)
    return vjp(dy)[0]


def native_wgrad(x, w, dy, stride, pad):
    _, vjp = jax.vjp(lambda w_: native_fwd(x, w_, stride, pad), w)
    return vjp(dy)[0]


# --- 1x1 reformulations (pad must be 0) ---

def mm_fwd_1x1(x, w, stride):
    if stride > 1:
        x = x[:, ::stride, ::stride, :]
    n, h, wd, ci = x.shape
    co = w.shape[0]
    y = lax.dot_general(x.reshape(n * h * wd, ci), w.reshape(co, ci),
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=x.dtype)
    return y.reshape(n, h, wd, co)


def mm_dgrad_1x1(dy, w, stride, in_h):
    n, h, wd, co = dy.shape
    ci = w.shape[-1]
    dx = lax.dot_general(dy.reshape(n * h * wd, co), w.reshape(co, ci),
                         (((1,), (0,)), ((), ())),
                         preferred_element_type=dy.dtype)
    dx = dx.reshape(n, h, wd, ci)
    if stride > 1:
        # scatter back to strided positions: interior-dilate + edge pad
        extra = in_h - ((h - 1) * stride + 1)
        dx = lax.pad(dx, jnp.zeros((), dx.dtype),
                     ((0, 0, 0), (0, extra, stride - 1),
                      (0, extra, stride - 1), (0, 0, 0)))
    return dx


def mm_wgrad_1x1(x, dy, stride):
    if stride > 1:
        x = x[:, ::stride, ::stride, :]
    n, h, wd, ci = x.shape
    co = dy.shape[-1]
    dw = lax.dot_general(dy.reshape(n * h * wd, co), x.reshape(n * h * wd, ci),
                         (((0,), (0,)), ((), ())),
                         preferred_element_type=x.dtype)
    return dw.reshape(co, 1, 1, ci)


# --- 3x3 wgrad via im2col matmul ---

def im2col_wgrad(x, dy, k, stride, pad):
    n, h, wd, ci = x.shape
    _, oh, ow, co = dy.shape
    patches = lax.conv_general_dilated_patches(
        x, (k, k), (stride, stride), [(pad, pad)] * 2,
        dimension_numbers=DN, preferred_element_type=x.dtype)
    # patches: (n, oh, ow, ci*k*k) with feature order (ci, kh, kw)
    p2 = patches.reshape(n * oh * ow, ci * k * k)
    dw = lax.dot_general(dy.reshape(n * oh * ow, co), p2,
                         (((0,), (0,)), ((), ())),
                         preferred_element_type=x.dtype)
    dw = dw.reshape(co, ci, k, k).transpose(0, 2, 3, 1)
    return dw


def time_compiled(fn, args, k1=10, k2=40, reps=2):
    c = jax.jit(fn).lower(*args).compile()
    out = c(*args)
    jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
    marg = []
    for _ in range(reps):
        el = {}
        for K in (k1, k2):
            t0 = time.perf_counter()
            for _i in range(K):
                out = c(*args)
            jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
            el[K] = time.perf_counter() - t0
        marg.append((el[k2] - el[k1]) / (k2 - k1))
    return min(marg)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()
    shapes = QUICK if args.quick else RESNET50_CONVS
    n = args.batch
    dev = jax.devices()[0]
    peak = 197e12 if "v5" in getattr(dev, "device_kind", "") else None
    print(f"device={dev.device_kind if hasattr(dev, 'device_kind') else dev}"
          f" batch={n}")
    rng = np.random.default_rng(0)
    rows = []
    for name, h, ci, co, k, stride in shapes:
        pad = k // 2 if k > 1 else 0
        oh = (h + 2 * pad - k) // stride + 1
        flops = 2 * n * oh * oh * k * k * ci * co
        x = jnp.asarray(rng.standard_normal((n, h, h, ci)), DT)
        w = jnp.asarray(rng.standard_normal((co, k, k, ci)), DT)
        dy = jnp.asarray(rng.standard_normal((n, oh, oh, co)), DT)
        row = {"name": name, "h": h, "ci": ci, "co": co, "k": k, "s": stride,
               "gflop": round(flops / 1e9, 2)}
        cases = {
            "fwd": (lambda x, w, dy: native_fwd(x, w, stride, pad)),
            "dgrad": (lambda x, w, dy: native_dgrad(x, w, dy, stride, pad)),
            "wgrad": (lambda x, w, dy: native_wgrad(x, w, dy, stride, pad)),
        }
        if k == 1:
            cases["mm_fwd"] = lambda x, w, dy: mm_fwd_1x1(x, w, stride)
            cases["mm_dgrad"] = lambda x, w, dy: mm_dgrad_1x1(dy, w, stride, h)
            cases["mm_wgrad"] = lambda x, w, dy: mm_wgrad_1x1(x, dy, stride)
        else:
            cases["im2col_wgrad"] = \
                lambda x, w, dy: im2col_wgrad(x, dy, k, stride, pad)
        for cname, fn in cases.items():
            try:
                dt = time_compiled(fn, (x, w, dy))
                eff = flops / dt / peak if peak else 0.0
                row[cname + "_us"] = round(dt * 1e6, 1)
                row[cname + "_eff"] = round(eff, 3)
            except Exception as e:
                row[cname + "_us"] = None
                print(f"  {name} {cname} FAILED: {e!r}")
        print(json.dumps(row))
        rows.append(row)
    # summary: where does the reformulation win?
    print("\n=== wins (reform faster than native) ===")
    for r in rows:
        for d in ("fwd", "dgrad", "wgrad"):
            alt = ("mm_" + d) if r["k"] == 1 else ("im2col_" + d)
            if r.get(alt + "_us") and r.get(d + "_us") and \
                    r[alt + "_us"] < r[d + "_us"]:
                print(f"{r['name']:12s} {d}: native {r[d+'_us']:8.1f}us "
                      f"(eff {r[d+'_eff']:.2f}) -> {alt} {r[alt+'_us']:8.1f}us "
                      f"(eff {r[alt+'_eff']:.2f})")


if __name__ == "__main__":
    main()
