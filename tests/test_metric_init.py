"""Metric + initializer tests (reference test_metric.py / test_init.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import metric, initializer


def test_accuracy():
    m = metric.create("acc")
    pred = mx.nd.array([[0.3, 0.7], [0.8, 0.2], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3) < 1e-6


def test_top_k():
    m = metric.create("top_k_accuracy", top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]])
    label = mx.nd.array([2, 1])
    m.update([label], [pred])
    _, acc = m.get()
    assert abs(acc - 1.0) < 1e-6  # both labels in top-2


def test_mse_mae_rmse():
    pred = mx.nd.array([1.0, 2.0, 3.0])
    label = mx.nd.array([1.5, 2.0, 2.5])
    for name, expect in [("mse", (0.25 + 0 + 0.25) / 3),
                         ("mae", (0.5 + 0 + 0.5) / 3)]:
        m = metric.create(name)
        m.update([label], [pred])
        assert abs(m.get()[1] - expect) < 1e-6
    m = metric.create("rmse")
    m.update([label], [pred])
    assert abs(m.get()[1] - np.sqrt(0.5 / 3)) < 1e-6


def test_perplexity():
    m = metric.create("perplexity", ignore_label=None)
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    m.update([label], [pred])
    _, ppl = m.get()
    expect = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert abs(ppl - expect) < 1e-5


def test_composite_and_custom():
    m = metric.create(["acc", "mse"])
    assert isinstance(m, metric.CompositeEvalMetric)

    def my_metric(label, pred):
        return float(np.sum(label == pred.argmax(axis=1))), label.shape[0]
    c = metric.np(my_metric)
    pred = mx.nd.array([[0.3, 0.7], [0.8, 0.2]])
    label = mx.nd.array([1, 0])
    c.update([label], [pred])
    assert c.get()[1] == 1.0


def test_initializers_shapes_and_stats():
    np.random.seed(0)
    for name, kwargs in [("uniform", {"scale": 0.1}),
                         ("normal", {"sigma": 0.01}),
                         ("xavier", {}),
                         ("msraprelu", {}),
                         ("orthogonal", {})]:
        init = initializer.create(name, **kwargs)
        arr = mx.nd.zeros((16, 8))
        init(initializer.InitDesc("fc1_weight"), arr)
        a = arr.asnumpy()
        assert a.shape == (16, 8)
        assert np.abs(a).sum() > 0

    # orthogonality
    o = mx.nd.zeros((8, 8))
    initializer.Orthogonal(scale=1.0)(initializer.InitDesc("q_weight"), o)
    q = o.asnumpy()
    np.testing.assert_allclose(q @ q.T, np.eye(8), atol=1e-5)


def test_magic_name_dispatch():
    init = initializer.Uniform(1.0)
    bias = mx.nd.ones((4,))
    init(initializer.InitDesc("fc1_bias"), bias)
    assert np.all(bias.asnumpy() == 0)
    gamma = mx.nd.zeros((4,))
    init(initializer.InitDesc("bn_gamma"), gamma)
    assert np.all(gamma.asnumpy() == 1)
    mv = mx.nd.ones((4,))
    init(initializer.InitDesc("bn_moving_mean"), mv)
    assert np.all(mv.asnumpy() == 0)


def test_attr_init_override():
    init = initializer.Zero()
    arr = mx.nd.zeros((4, 4))
    desc = initializer.InitDesc("custom", attrs={"__init__": initializer.One().dumps()[2:5]})
    # __init__ attr carries a registered name; use "one"
    desc = initializer.InitDesc("custom", attrs={"__init__": "one"})
    init(desc, arr)
    assert np.all(arr.asnumpy() == 1)


def test_mixed_and_constant():
    init = initializer.Mixed([".*fc2.*", ".*"],
                             [initializer.Constant(3.0), initializer.Uniform(0.1)])
    w = mx.nd.zeros((4, 4))
    init("fc2_weight", w)
    assert np.all(w.asnumpy() == 3.0)
    # magic-name dispatch still applies inside Mixed (reference semantics)
    b = mx.nd.ones((4,))
    init("fc1_bias", b)
    assert np.all(b.asnumpy() == 0.0)


def test_bilinear():
    arr = mx.nd.zeros((1, 1, 4, 4))
    initializer.Bilinear()(initializer.InitDesc("up_weight"), arr)
    a = arr.asnumpy()[0, 0]
    assert a.max() <= 1.0 and a[1, 1] > a[0, 0]
