"""Sparse linear-regression end-to-end — the reference's flagship sparse
workload (benchmark/python/sparse/sparse_end2end.py) on the TPU-native
stack, O(nnz) at EVERY tier:

  * csr input batches (criteo-like: few active features per sample),
    built directly in csr form — no dense (batch, feature_dim) staging
  * the weight lives ROW-SPARSE everywhere: the kvstore holds the
    compressed master copy, the device holds only the rows the current
    batch touches (a static-capacity RSPValue inside the jit graph), and
    `dot(csr, w_rsp)` gathers stored rows by id — the dense
    (feature_dim, 1) matrix never exists, host or device
  * the executor emits a ROW-SPARSE gradient (grad_stype inference,
    executor._resolve_grad_storage): jax.vjp over the RSPValue pytree
    produces the O(nnz) cotangent directly; `kv.push` of that rsp grad
    and the kvstore-held SGD's lazy_update keep update+comm O(nnz)

This mirrors the reference's split (device compute / ps-lite servers kept
sparse, indexing_op.cc SparseEmbeddingOpBackwardRsp +
kvstore_dist_server.h rsp path), with XLA's static-shape constraint met
by padding each batch's touched-row list to one fixed capacity.

Run: python examples/sparse_end2end.py [--num-batches 50]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402


def make_batches(rng, num_batches, batch_size, feature_dim, nnz_per_row):
    """Synthetic criteo-like stream, built directly as csr (no dense
    (batch, feature_dim) staging array)."""
    w_true = (rng.standard_normal(feature_dim) *
              (rng.random(feature_dim) < 0.5)).astype(np.float32)
    batches = []
    for _ in range(num_batches):
        # sample WITHOUT replacement per row: constant nnz per batch keeps
        # one compiled executable across the stream (static shapes)
        idx = np.stack([np.sort(rng.choice(feature_dim, nnz_per_row,
                                           replace=False))
                        for _ in range(batch_size)]).astype(np.int64)
        val = rng.standard_normal((batch_size, nnz_per_row)) \
            .astype(np.float32)
        y = (val * w_true[idx]).sum(axis=1) \
            + 0.01 * rng.standard_normal(batch_size).astype(np.float32)
        csr = mx.nd.sparse.csr_matrix(
            (val.reshape(-1), idx.reshape(-1),
             np.arange(0, batch_size * nnz_per_row + 1, nnz_per_row)),
            shape=(batch_size, feature_dim))
        batches.append((csr, mx.nd.array(y.astype(np.float32)),
                        np.unique(idx)))
    return batches, w_true


def _pad_rows(touched, cap):
    """Pad a batch's touched-row list to the stream-wide static capacity
    by repeating the last id (keeps ascending order; the push-side merge
    dedups, so duplicate padding rows are harmless)."""
    out = np.empty(cap, np.int64)
    out[:len(touched)] = touched
    out[len(touched):] = touched[-1]
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-batches", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--feature-dim", type=int, default=1000)
    ap.add_argument("--nnz-per-row", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args(argv)

    rng = np.random.default_rng(0)
    batches, w_true = make_batches(rng, args.num_batches, args.batch_size,
                                   args.feature_dim, args.nnz_per_row)
    D = args.feature_dim
    cap = max(len(t) for _, _, t in batches)

    # symbol: csr data -> sparse dot -> linear regression head.  `w` is
    # bound row-sparse, so inside the graph it is a static-capacity
    # RSPValue and its gradient comes back row-sparse (O(cap))
    data = mx.sym.Variable("data", stype="csr")
    w = mx.sym.Variable("w", stype="row_sparse")
    pred = mx.sym.dot(data, w)
    net = mx.sym.LinearRegressionOutput(pred, name="lro")

    pulled = mx.nd.sparse.row_sparse_array(
        (np.zeros((cap, 1), np.float32), np.zeros(cap, np.int64)),
        shape=(D, 1))
    arg_arrays = {
        "data": batches[0][0],
        "w": pulled,
        "lro_label": mx.nd.zeros((args.batch_size, 1)),
    }
    grad_req = {"data": "null", "lro_label": "null", "w": "write"}
    exe = net.bind(mx.cpu(), args=arg_arrays, grad_req=grad_req)

    # kvstore holds the ROW-SPARSE master weight + the optimizer
    # (update_on_kvstore, reference style)
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.sparse.row_sparse_array(
        (np.zeros((0, 1), np.float32), np.zeros(0, np.int64)),
        shape=(D, 1)))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=args.lr,
                                         momentum=0.9, wd=1e-5))

    def pull_batch_rows(touched):
        rows = mx.nd.array(_pad_rows(touched, cap).astype(np.float32))
        kv.row_sparse_pull("w", out=pulled, row_ids=rows)
        exe.arg_dict["w"] = pulled

    def eval_loss():
        """MSE over the whole stream with the CURRENT server weight —
        forward-only, still pulling just each batch's touched rows."""
        tot = 0.0
        for csr_batch, y, touched in batches:
            pull_batch_rows(touched)
            exe.arg_dict["data"] = csr_batch
            exe.arg_dict["lro_label"][:] = y.asnumpy()[:, None]
            (out,) = exe.forward(is_train=False)
            tot += float(np.square(out.asnumpy()[:, 0]
                                   - y.asnumpy()).mean())
        return tot / len(batches)

    first_loss = eval_loss()
    t0 = time.perf_counter()
    n_samples = 0
    for epoch in range(args.epochs):
        for csr_batch, y, touched in batches:
            pull_batch_rows(touched)
            exe.arg_dict["data"] = csr_batch
            exe.arg_dict["lro_label"][:] = y.asnumpy()[:, None]
            exe.forward(is_train=True)
            exe.backward()
            # the gradient comes out of the executor ALREADY row-sparse
            # (indices = the pulled rows); push is O(cap)
            g_rsp = exe.grad_dict["w"]
            assert g_rsp.stype == "row_sparse", g_rsp.stype
            kv.push("w", g_rsp)
            n_samples += args.batch_size
    dt = time.perf_counter() - t0
    last_loss = eval_loss()
    print("sparse_end2end: %d samples in %.2fs (%.0f samples/s), "
          "eval mse %.4f -> %.4f, grad stype=%s"
          % (n_samples, dt, n_samples / dt, first_loss, last_loss,
             exe.grad_dict["w"].stype))
    return first_loss, last_loss


if __name__ == "__main__":
    main()
