"""VGG 11/13/16/19, with and without BatchNorm, table-driven
(Simonyan & Zisserman 1409.1556; reference architecture:
python/mxnet/gluon/model_zoo/vision/vgg.py).

One spec table (convs-per-stage x stage widths) expands into a row list
for the shared assembler; the classifier tail is three Dense rows.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ....initializer import Xavier
from ._builder import assemble, named_factory

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn",
           "vgg16_bn", "vgg19_bn", "get_vgg"]

vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}

_CONV_INIT = {"init": Xavier(rnd_type="gaussian", factor_type="out",
                             magnitude=2)}


def _feature_rows(layers, filters, batch_norm):
    rows = []
    for count, width in zip(layers, filters):
        for _ in range(count):
            rows.append(("conv", width, 3, 1, 1, _CONV_INIT))
            if batch_norm:
                rows.append(("bn",))
            rows.append(("relu",))
        rows.append(("pool", 2, 2, 0))
    for _ in range(2):
        rows += [("dense", 4096, {"act": "relu", "init": "normal"}),
                 ("dropout", 0.5)]
    return rows


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = assemble(
                nn.HybridSequential(prefix=""),
                _feature_rows(layers, filters, batch_norm))
            self.output = nn.Dense(classes, weight_initializer="normal",
                                   bias_initializer="zeros")

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        from ..model_store import get_model_file
        tag = "_bn" if kwargs.get("batch_norm") else ""
        net.load_params(get_model_file("vgg%d%s" % (num_layers, tag),
                                       root=root), ctx=ctx)
    return net


vgg11 = named_factory("vgg11", get_vgg, 11)
vgg13 = named_factory("vgg13", get_vgg, 13)
vgg16 = named_factory("vgg16", get_vgg, 16)
vgg19 = named_factory("vgg19", get_vgg, 19)
vgg11_bn = named_factory("vgg11_bn", get_vgg, 11, batch_norm=True)
vgg13_bn = named_factory("vgg13_bn", get_vgg, 13, batch_norm=True)
vgg16_bn = named_factory("vgg16_bn", get_vgg, 16, batch_norm=True)
vgg19_bn = named_factory("vgg19_bn", get_vgg, 19, batch_norm=True)
