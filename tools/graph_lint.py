#!/usr/bin/env python
"""Graph linter: run the mxnet_tpu.analysis pass suite from the shell.

No reference analog — the reference has no pre-compile analysis layer
at all (errors surface at bind/dispatch).  This CLI runs the IR
verifier, the shape/dtype abstract interpreter, the retrace-hazard
linter, and the padding-soundness classifier over a serialized symbol
JSON or a named model-zoo graph, and prints every finding with its
node-level provenance.

Usage:
    # lint a checkpoint graph at a concrete input shape
    python tools/graph_lint.py model-symbol.json \
        --shapes data=8,3,224,224

    # lint exemplar graphs by name (models/ + gluon model_zoo)
    python tools/graph_lint.py mlp resnet18_v1 --strict

    # serving-shaped question: is seq bucketing sound for this graph?
    python tools/graph_lint.py model-symbol.json \
        --shapes data=8,0,64 --seq-axis 1 --seq-buckets 32,64

    # repair it: splice valid-length masks before every cross-position
    # frontier, re-verify, and emit <stem>.repaired.json + a report
    python tools/graph_lint.py model-symbol.json \
        --shapes data=8,4,64 --seq-axis 1 --seq-buckets 4 --fix

    # optimize it: run the verdict-gated pass pipeline (CSE, constant
    # folding, DCE, algebraic identities; analysis/optimize.py), emit
    # <stem>.optimized.json + per-pass before/after node counts
    python tools/graph_lint.py model-symbol.json \
        --shapes data=8,3,224,224 --optimize

    # continuous-batching decode: is the masked step row-local along
    # the SLOT axis (axis 0), with state inputs seeded pad-dirty?
    # Also reports the fused-op selections (op, site, verdict) the
    # optimizer's selection stage would make on this step — the
    # offline audit of MXNET_OPT_SELECT_KERNELS kernel swaps.  The
    # selection report is ADVISORY: it never moves the exit code
    # (--decode-step exits on the verdict/findings exactly as before;
    # a rejected selection plan shows up as verdict "rejected: ...",
    # not as a failure)
    python tools/graph_lint.py step-symbol.json --decode-step --json \
        --shapes token=8 --shapes h=8,32 --shapes c=8,32 \
        --decode-state h,c

    # memory plan: per-program predicted peak HBM + top contributors,
    # donation soundness, in-place candidates (analysis/memory.py) —
    # the offline view of the engines' OOM preflight.  Composes with
    # --decode-step (slot-pool shapes; --decode-state names donate
    # into outputs 1+i, the engine's in-place pool contract) and
    # --sharding-plan (bytes divide along plan-partitioned axes)
    python tools/graph_lint.py step-symbol.json --decode-step --memory \
        --shapes token=8 --shapes h=8,32 --shapes c=8,32 \
        --decode-state h,c

Dynamic dims are written as 0 (or '?') in --shapes; the retrace linter
keys on them.  --strict exits nonzero on warnings too (CI bar: the
model-zoo exemplars must lint clean — tests/test_graph_lint.py).

Exit codes (documented contract, tests/test_graph_lint.py):
  0  clean at the chosen bar
  1  warnings only, failing the bar (--strict; or a rejected --fix)
  2  hard failure: verifier/shape ERRORS, or a graph could not load
--memory interacts with the bar like --fix does: an UNSOUND donation
spec exits 1 even without --strict — it means the declared in-place
aliasing would clobber a buffer before its last read, exactly the
verdict the engines warn (or refuse, under MXNET_ANALYSIS_STRICT=1)
on at construction.  The peak/contributor/in-place report itself is
ADVISORY and never moves the exit code.
--optimize interacts with the bar like --fix does: a REJECTED
optimization plan (the candidate's re-analysis verdicts came back
worse — an optimizer bug, never a user error) exits 1 even without
--strict, while an accepted plan — including the common
"nothing to rewrite" outcome — leaves the exit code to the findings
themselves; --strict stays a property of the findings, not of how
many rewrites were applied.  --optimize runs on the input graph as
analyzed; to optimize a --fix artifact, re-run on the emitted
<stem>.repaired.json.
With --fix, a graph whose cross-position verdicts are all repaired
(and whose rewritten graph re-lints clean) counts as passing; the
repaired symbol JSON lands next to the input (or --fix-dir).  When
only SOME labels repaired, the artifact is named
<stem>.repaired.partial.json instead — it is still cross-position
along the rejected axes — and the run keeps its failing exit code.

--json prints one machine-readable document (findings with node/op/
provenance/fingerprint, per-axis verdicts, repair outcomes, and — with
--optimize — an "optimization" section: per-pass applied/rejected
action counts, nodes before/after, rejection reasons, the analytic
FLOP delta, and fusion hints) instead of text — tools/hazard_rank.py
joins it against telemetry snapshots.
"""
from __future__ import annotations

import argparse
import os
import sys

if __package__ is None or __package__ == "":       # script invocation
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# model-zoo exemplars the CI lint step sweeps (name -> builder, shapes)
_ZOO = {
    "mlp": ("mxnet_tpu.models.lenet", "get_mlp", {"data": (8, 784)}),
    "lenet": ("mxnet_tpu.models.lenet", "get_lenet",
              {"data": (8, 1, 28, 28)}),
    "resnet18": ("mxnet_tpu.models.resnet", "get_resnet_symbol",
                 {"data": (4, 3, 32, 32)}),
    "resnet50": ("mxnet_tpu.models.resnet", "get_resnet_symbol",
                 {"data": (4, 3, 32, 32)}),
}
_ZOO_KWARGS = {
    "resnet18": dict(num_classes=10, num_layers=18, image_shape=(3, 32, 32)),
    "resnet50": dict(num_classes=10, num_layers=50, image_shape=(3, 32, 32)),
}


def _load_graph(spec):
    """Resolve one positional arg: a symbol JSON path, a models/ name,
    or a gluon model_zoo name.  Returns (symbol, default_shapes)."""
    import importlib
    if spec.endswith(".json") or os.path.sep in spec or \
            os.path.exists(spec):
        from mxnet_tpu import symbol as sym
        return sym.load(spec), {}
    if spec in _ZOO:
        mod_name, fn_name, shapes = _ZOO[spec]
        builder = getattr(importlib.import_module(mod_name), fn_name)
        return builder(**_ZOO_KWARGS.get(spec, {})), dict(shapes)
    # gluon model_zoo names (resnet18_v1, mobilenet1.0, ...): blocks
    # compose symbolically, so feeding a Variable traces the Symbol
    from mxnet_tpu import sym as _s
    from mxnet_tpu.gluon.model_zoo import get_model
    net = get_model(spec)
    return net(_s.Variable("data")), {"data": (4, 3, 224, 224)}


def _parse_shapes(entries):
    shapes = {}
    for e in entries or ():
        if "=" not in e:
            raise ValueError("--shapes entries look like name=1,3,224,224"
                             " (got %r)" % e)
        name, dims = e.split("=", 1)
        # dynamic dims are spelled 0 or ?; empty segments (a trailing
        # comma) are ignored rather than read as phantom dynamic dims
        shape = tuple(0 if d.strip() == "?" else int(d)
                      for d in dims.split(",") if d.strip())
        shapes[name.strip()] = shape
    return shapes


def _build_policy(args):
    if args.seq_axis is None and not args.seq_buckets:
        if args.max_batch is None:
            return None
        from mxnet_tpu.serving import BucketPolicy
        return BucketPolicy(max_batch=args.max_batch)
    from mxnet_tpu.serving import BucketPolicy
    buckets = tuple(int(b) for b in (args.seq_buckets or "").split(",")
                    if b.strip())
    return BucketPolicy(max_batch=args.max_batch or 8,
                        seq_axis=args.seq_axis if buckets else None,
                        seq_buckets=buckets)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="static analysis over Symbol graphs "
                    "(mxnet_tpu.analysis)")
    ap.add_argument("graphs", nargs="+",
                    help="symbol JSON path(s) and/or model names: %s or "
                         "any gluon model_zoo name" % sorted(_ZOO))
    ap.add_argument("--shapes", action="append", metavar="NAME=D0,D1,..",
                    help="input shapes; 0 or ? marks a dynamic dim "
                         "(repeatable)")
    ap.add_argument("--passes", default=None,
                    help="comma list (default: verify,shapes,retrace,"
                         "padding)")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="declare the serving batch-bucket grid")
    ap.add_argument("--seq-axis", type=int, default=None,
                    help="graph axis the serving seq buckets pad")
    ap.add_argument("--seq-buckets", default="",
                    help="comma list of seq bucket sizes")
    ap.add_argument("--training", action="store_true",
                    help="analyze training mode (BatchNorm batch stats "
                         "etc.); default is inference")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on warnings too, not just errors")
    ap.add_argument("--fix", action="store_true",
                    help="attempt masking repairs of cross-position "
                         "verdicts (analysis/rewrite.py); emit "
                         "<stem>.repaired.json + a repair report")
    ap.add_argument("--optimize", action="store_true",
                    help="run the verdict-gated optimizing pass "
                         "pipeline (analysis/optimize.py: algebraic, "
                         "fold, cse, dce + fusion hints); emit "
                         "<stem>.optimized.json when rewrites were "
                         "accepted and report per-pass node counts")
    ap.add_argument("--fix-dir", default=None,
                    help="directory for --fix/--optimize outputs "
                         "(default: next to the input JSON, or the "
                         "cwd for model names)")
    ap.add_argument("--decode-step", action="store_true",
                    help="lint a continuous-batching decode STEP graph "
                         "(serving/decode.py): axis 0 of every --shapes "
                         "input is the slot-pool axis, and the verdict "
                         "must be row-local along it — a dead slot's "
                         "stale values must never reach a live slot's "
                         "outputs.  State inputs (--decode-state) are "
                         "seeded pad-DIRTY, so even zero-absorbing "
                         "reductions over them count as violations.  A "
                         "cross-position slot verdict exits 1 even "
                         "without --strict: the decode engine has no "
                         "degrade path, unsound means unserveable")
    ap.add_argument("--decode-state", default="", metavar="N1,N2,..",
                    help="with --decode-step: comma list of slot-state "
                         "input names (KV cache / recurrent state "
                         "buffers; freed slots leave stale garbage in "
                         "them, so they get no zero-pad credit)")
    ap.add_argument("--decode-valid", default=None, metavar="NAME",
                    help="with --decode-step: name of the slot-"
                         "occupancy/valid vector input, if the step "
                         "graph masks on one")
    ap.add_argument("--draft", default=None, metavar="JSON",
                    help="with --decode-step: audit a speculative "
                         "draft/target PAIR (serving/spec.py, "
                         "MXNET_DECODE_SPEC_K): the draft symbol "
                         "JSON is linted through the same slot-axis "
                         "classifier (its states ride the same pool), "
                         "the two heads are checked for vocabulary/"
                         "layout compatibility (a mismatch means "
                         "DecodeEngine would refuse construction: "
                         "exit 1, like a cross-position draft "
                         "verdict), and the report carries the "
                         "would-be _cache_write_rows commit selection "
                         "for the declared cache states — the "
                         "selection half is ADVISORY and never moves "
                         "the exit code, exactly like the single-row "
                         "selection report")
    ap.add_argument("--draft-shapes", action="append",
                    metavar="NAME=D0,D1,..",
                    help="with --draft: the draft graph's input "
                         "shapes (full slot-pool shapes, like "
                         "--shapes; repeatable)")
    ap.add_argument("--draft-state", default="", metavar="N1,N2,..",
                    help="with --draft: comma list of the draft "
                         "graph's slot-state input names")
    ap.add_argument("--spec-k", type=int, default=2, metavar="K",
                    help="with --draft: speculative window width the "
                         "commit-selection audit assumes (default 2; "
                         "the engine knob is MXNET_DECODE_SPEC_K)")
    ap.add_argument("--decode-cache", default="", metavar="N1,N2,..",
                    help="with --draft: target state names declared "
                         "cache-like ({'cache': True} in state_info: "
                         "the step writes exactly row pos[i] per "
                         "token) — the states the multi-token commit "
                         "audit builds its graph over")
    ap.add_argument("--draft-cache", default="", metavar="N1,N2,..",
                    help="with --draft: the draft graph's cache-like "
                         "state names")
    ap.add_argument("--sharding-plan", default=None, metavar="JSON",
                    help="audit a model-parallel ShardingPlan spec "
                         "(parallel/mesh.py; inline JSON or a file "
                         "path) against this graph's padded-axis "
                         "verdicts: reports which nodes the plan "
                         "partitions (everything downstream of a "
                         "partitioned input under computation-follows-"
                         "data) and the verdict per partitioned padded "
                         "axis.  A REJECTED plan — one partitioning a "
                         "cross-position or unproven padded axis — "
                         "exits 1 even without --strict, exactly the "
                         "gate ServingEngine/DecodeEngine apply at "
                         "construction.  Combines with --decode-step "
                         "(slot-axis verdict) or the serve-mode "
                         "padded-axis verdicts")
    ap.add_argument("--memory", action="store_true",
                    help="run the static memory planner "
                         "(analysis/memory.py) over each graph: "
                         "predicted peak HBM (params resident + "
                         "liveness high-water), top per-node "
                         "contributors, in-place candidates, and the "
                         "donation soundness verdict.  With "
                         "--decode-step the --decode-state inputs are "
                         "priced as the engine's donated slot pool "
                         "(state i aliases output 1+i) unless --donate "
                         "overrides; with --sharding-plan the bytes "
                         "divide along plan-partitioned axes.  An "
                         "UNSOUND donation exits 1 even without "
                         "--strict; the rest is advisory")
    ap.add_argument("--donate", default="", metavar="N1=O1,N2=O2,..",
                    help="with --memory: explicit donation spec — "
                         "input NAME aliases output index O (the "
                         "buffer is reused in place).  Overrides the "
                         "--decode-state-derived spec")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print one machine-readable JSON document "
                         "instead of text (hazard_rank.py input)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only graphs with findings")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import json
    from mxnet_tpu import analysis

    try:
        cli_shapes = _parse_shapes(args.shapes)
        policy = _build_policy(args)
    except Exception as e:
        print("graph_lint: %s" % e, file=sys.stderr)
        return 2

    plan_spec = None
    if args.sharding_plan is not None:
        from mxnet_tpu.parallel.mesh import load_plan_spec
        try:
            plan_spec = load_plan_spec(args.sharding_plan)
        except Exception as e:
            print("graph_lint: bad --sharding-plan: %s" % e,
                  file=sys.stderr)
            return 2

    if args.decode_step and (args.fix or args.optimize
                             or args.seq_axis is not None
                             or args.seq_buckets):
        print("graph_lint: --decode-step lints the step graph as-is "
              "along the slot axis and cannot combine with --fix/"
              "--optimize/--seq-axis/--seq-buckets", file=sys.stderr)
        return 2

    passes = tuple(p.strip() for p in args.passes.split(",")
                   if p.strip()) if args.passes else None
    worst = 0
    doc = {}
    for spec in args.graphs:
        try:
            graph, shapes = _load_graph(spec)
        except Exception as e:
            print("graph_lint: cannot load %r: %s" % (spec, e),
                  file=sys.stderr)
            if args.as_json:
                # --json promises ONE document: record the failure and
                # keep the graphs already analyzed instead of dropping
                # the whole report on the floor (exit still 2)
                doc[spec] = {"load_error": str(e)}
                worst = 2
                continue
            return 2
        shapes.update(cli_shapes)
        if args.decode_step:
            state_names = [s.strip() for s in
                           args.decode_state.split(",") if s.strip()]
            verdict, report = analysis.check_decode_step(
                graph, shapes, state_names=state_names,
                valid_name=args.decode_valid, training=args.training)
            hard = bool(report.errors)
            unsound = verdict == "cross-position"
            failed = unsound or not report.clean(strict=args.strict)
            # fused-op selection audit (advisory, never moves the exit
            # code): which kernel swaps the optimizer's selection stage
            # WOULD make on this step graph, and whether the verdict-
            # gated plan accepts them — so operators can audit what
            # MXNET_OPT_SELECT_KERNELS will serve, offline, before a
            # deploy flips the knob
            selections = []
            if not hard:
                selections = _decode_selections(
                    analysis, graph, shapes, state_names,
                    args.decode_valid, args.training)
            plan_audit = None
            if plan_spec is not None and not hard:
                plan_audit = _audit_plan(analysis, graph, plan_spec,
                                         "decode", {"slot": verdict},
                                         shapes)
                if not plan_audit["accepted"]:
                    failed = True
            draft_audit = None
            if args.draft is not None and not hard:
                draft_audit, draft_bad = _audit_draft_pair(
                    analysis, graph, shapes, args)
                if draft_bad:
                    failed = True
            mem_audit = None
            if args.memory and not hard:
                # the engine's slot-pool donation contract by default:
                # state i aliases output 1+i
                donate = _parse_donate(args.donate) or {
                    nm: 1 + i for i, nm in enumerate(state_names)}
                mem_audit, mem_bad = _audit_memory(
                    graph, shapes, donate=donate,
                    state_names=state_names, plan_spec=plan_spec,
                    training=args.training)
                if mem_bad:
                    failed = True
            doc[spec] = {"findings": report.to_list(),
                         "verdicts": {"slot": verdict}, "repairs": [],
                         "selections": selections,
                         "spec": draft_audit,
                         "sharding_plan": plan_audit,
                         "memory": mem_audit}
            if not args.as_json and (failed or not args.quiet):
                print("== %s ==" % spec)
                print(report.format())
                print("  decode-step slot axis: %s" % verdict)
                for s in selections:
                    print("  fused-op selection: %s at %s (%s)"
                          % (s["op"], s["site"], s["verdict"]))
                _print_draft_audit(draft_audit)
                _print_plan_audit(plan_audit)
                _print_memory_audit(mem_audit)
                if unsound:
                    print("  FAIL: step graph is cross-position along "
                          "the slot axis — a dead slot's stale state "
                          "reaches live outputs; DecodeEngine cannot "
                          "serve it")
            if hard:
                worst = 2
            elif failed:
                worst = max(worst, 1)
            continue
        shapes, valid_vars = _shape_valid_lengths(graph, shapes)
        pad_axes = None
        if policy is not None and policy.seq_axis is not None:
            data_inputs = [n for n in shapes if n not in valid_vars]
            pad_axes = {"batch": {n: 0 for n in shapes},
                        "seq": {n: policy.seq_axis for n in data_inputs}}
        report, ctx = analysis.analyze(
            graph, data_shapes=shapes, policy=policy, pad_axes=pad_axes,
            training=args.training, passes=passes)
        failed = not report.clean(strict=args.strict)
        hard = bool(report.errors)
        entry = {"findings": report.to_list(),
                 "verdicts": dict(ctx.pad_verdicts), "repairs": []}
        if plan_spec is not None and not hard:
            entry["sharding_plan"] = _audit_plan(
                analysis, graph, plan_spec, "serve",
                dict(ctx.pad_verdicts), shapes)
            if not entry["sharding_plan"]["accepted"]:
                failed = True
        if args.memory and not hard:
            entry["memory"], mem_bad = _audit_memory(
                graph, shapes, donate=_parse_donate(args.donate),
                state_names=(), plan_spec=plan_spec,
                training=args.training)
            if mem_bad:
                failed = True
        fix_lines = []
        if args.fix and pad_axes is None and not hard:
            # --fix must never be a silent no-op: say WHY no repair
            # was attempted (repairs need the seq padded-axis spec)
            reason = ("--fix: no padded-axis spec — pass --seq-axis/"
                      "--seq-buckets to describe the bucketing to "
                      "repair for (batch-only padding has no masking "
                      "repair: cross-position batch graphs serve at "
                      "max_batch=1)")
            entry["repairs"].append({"label": None, "accepted": False,
                                     "reason": reason})
            fix_lines.append(reason)
        elif args.fix and pad_axes is not None and not hard:
            failed, hard = _fix_graph(
                analysis, spec, graph, shapes, pad_axes, policy, args,
                passes, report, ctx, entry, fix_lines, failed, hard)
        if args.optimize and not hard:
            # the analysis above already covered this exact graph/spec
            # whenever the default (full) pass set ran — forward it so
            # --optimize pays for one candidate re-analysis, not a
            # repeated pre-analysis.  --fix may have changed shapes/
            # pad_axes for the NEXT analysis, but optimizes the input
            # graph under the ORIGINAL spec, so only reuse when no
            # repair ran.
            pre = (report, ctx) if passes is None \
                and not (args.fix and entry["repairs"]) else None
            failed = _optimize_graph_cli(
                analysis, spec, graph, shapes, pad_axes, policy, args,
                entry, fix_lines, failed, pre)
        doc[spec] = entry
        if not args.as_json and (failed or not args.quiet):
            print("== %s ==" % spec)
            print(report.format())
            for label, verdict in sorted(ctx.pad_verdicts.items()):
                print("  padded %s axis: %s" % (label, verdict))
            _print_plan_audit(entry.get("sharding_plan"))
            _print_memory_audit(entry.get("memory"))
            for ln in fix_lines:
                print(ln)
        if hard:
            worst = 2
        elif failed:
            worst = max(worst, 1)
    if args.as_json:
        print(json.dumps({"graphs": doc}, indent=2, default=str))
    return worst


def _audit_plan(analysis, graph, plan_spec, kind, verdicts, shapes):
    """Run the offline sharding-plan audit over one graph: the SAME
    ``check_sharding_plan`` gate the engines apply at construction,
    plus the node attribution (everything downstream of a partitioned
    input) only an offline tool has the budget to walk."""
    try:
        check, detail = analysis.audit_sharding_plan(
            graph, plan_spec, data_shapes=shapes, kind=kind,
            verdicts=verdicts)
    except Exception as e:
        return {"accepted": False,
                "reasons": ["audit crashed: %s" % e],
                "partitioned": [], "nodes": {}}
    return {"accepted": check.accepted, "reasons": check.reasons,
            "partitioned": check.partitioned, "nodes": detail["nodes"]}


def _print_plan_audit(audit):
    if audit is None:
        return
    print("  sharding plan: %s"
          % ("ACCEPTED" if audit["accepted"] else "REJECTED"))
    for row in audit["partitioned"]:
        where = row.get("padded_axis") or row.get("rule") or "param"
        print("    partitions %s (%s): verdict %s"
              % (row["input"], where, row.get("verdict")))
    for src, nodes in sorted(audit["nodes"].items()):
        show = ", ".join(nodes[:6]) + (", ..." if len(nodes) > 6 else "")
        print("    %s reaches %d node(s): %s" % (src, len(nodes), show))
    for r in audit["reasons"]:
        print("    FAIL: %s" % r)


def _parse_donate(entry):
    """--donate "h=1,c=2" -> {"h": 1, "c": 2} (empty -> None)."""
    donate = {}
    for e in (entry or "").split(","):
        if not e.strip():
            continue
        if "=" not in e:
            raise ValueError("--donate entries look like name=out_idx"
                             " (got %r)" % e)
        name, idx = e.split("=", 1)
        donate[name.strip()] = int(idx)
    return donate or None


def _audit_memory(graph, shapes, donate, state_names, plan_spec,
                  training):
    """--memory: the offline view of the engines' OOM preflight —
    one program's liveness plan (predicted peak, top contributors,
    in-place candidates) plus the donation soundness verdict.
    Returns ``(audit dict, failed)``: only an UNSOUND donation fails
    the run (the engines' construction-time bar); everything else is
    advisory."""
    from mxnet_tpu.analysis.memory import plan_memory
    try:
        plan, report = plan_memory(
            graph, shapes, training=training, sharding=plan_spec,
            donate=donate or None, state_names=tuple(state_names))
    except Exception as e:
        return {"error": "memory planner crashed: %s" % e}, False
    if not plan:
        return {"error": "memory pass produced no plan",
                "findings": report.to_list()}, False
    out = {k: plan[k] for k in
           ("peak_bytes", "param_bytes", "input_bytes", "output_bytes",
            "transient_peak_bytes", "per_node_top", "inplace",
            "inplace_savings_bytes", "donation", "sharded",
            "skipped_nodes")}
    bad = (plan["donation"] is not None
           and not plan["donation"]["accepted"])
    return out, bad


def _print_memory_audit(mem):
    if mem is None:
        return
    from mxnet_tpu.analysis.memory import format_bytes
    if mem.get("error"):
        print("  memory: %s" % mem["error"])
        return
    print("  memory: predicted peak %s (params %s + transient %s%s%s)"
          % (format_bytes(mem["peak_bytes"]),
             format_bytes(mem["param_bytes"]),
             format_bytes(mem["transient_peak_bytes"]),
             ", sharded" if mem["sharded"] else "",
             (", %d skipped — lower bound" % mem["skipped_nodes"])
             if mem["skipped_nodes"] else ""))
    for row in mem["per_node_top"]:
        print("    top contributor: %s (%s) out %s, live-set %s"
              % (row["node"], row["op"], format_bytes(row["out_bytes"]),
                 format_bytes(row["live_bytes"])))
    if mem["inplace"]:
        print("    in-place candidates: %d op(s), %s reclaimable"
              % (len(mem["inplace"]),
                 format_bytes(mem["inplace_savings_bytes"])))
    d = mem["donation"]
    if d is not None:
        print("    donation: %s (%d input(s))"
              % ("SOUND" if d["accepted"] else "UNSOUND",
                 len(d["per_input"])))
        for r in d["reasons"]:
            print("    FAIL: %s" % r)


def _head_dtype(analysis, graph, shapes):
    """The inferred dtype of a graph's first output (the logits
    head), via the shape/dtype abstract interpreter."""
    _report, ctx = analysis.analyze(graph, data_shapes=shapes,
                                    passes=("verify", "shapes"))
    n0, i0 = graph._outputs[0]
    dt = ctx.node_dtypes.get((id(n0), i0))
    return str(dt) if dt is not None else None


def _audit_draft_pair(analysis, target, shapes, args):
    """--draft: the offline audit of a speculative draft/target pair
    (serving/spec.py).  Checks the things DecodeEngine checks at
    construction — the draft's own slot-axis verdict (its states ride
    the same pool) and head compatibility (same vocabulary, same
    logits layout, same dtype) — plus the ADVISORY would-be
    ``_cache_write_rows`` commit selection over the declared cache
    states.  Returns ``(audit dict, failed)``: a cross-position/
    unverifiable draft or an incompatible head fails the run (the
    engine would refuse or mis-serve), the selection report never
    does."""
    out = {"draft": args.draft, "k": args.spec_k}
    bad = False
    from mxnet_tpu import symbol as sym_mod
    try:
        draft = sym_mod.load(args.draft)
    except Exception as e:
        return {"draft": args.draft,
                "error": "cannot load draft: %s" % e}, True
    try:
        dshapes = _parse_shapes(args.draft_shapes)
    except Exception as e:
        return {"draft": args.draft, "error": str(e)}, True
    d_states = [s.strip() for s in args.draft_state.split(",")
                if s.strip()]
    dverdict, dreport = analysis.check_decode_step(
        draft, dshapes, state_names=d_states,
        valid_name=args.decode_valid
        if args.decode_valid in draft.list_arguments() else None,
        training=args.training)
    out["draft_verdicts"] = {"slot": dverdict}
    out["draft_findings"] = dreport.to_list()
    if dreport.errors or dverdict != "row-local":
        bad = True
    # head compatibility: acceptance compares draft proposals against
    # the target distribution index-for-index.  The shape (vocab +
    # layout) check mirrors the engine's construction gate and FAILS
    # the run; the dtype comparison is reported but ADVISORY — the
    # accept logic casts both heads, so mixed precision serves (the
    # engine accepts it), it just merits an operator's look.
    head = {}
    try:
        _a, t_out, _x = target.infer_shape(**shapes)
        _a2, d_out, _x2 = draft.infer_shape(**dshapes)
        head["target"] = list(t_out[0])
        head["draft"] = list(d_out[0])
        head["target_dtype"] = _head_dtype(analysis, target, shapes)
        head["draft_dtype"] = _head_dtype(analysis, draft, dshapes)
        head["dtype_match"] = (head["target_dtype"]
                               == head["draft_dtype"])
        head["compatible"] = tuple(t_out[0]) == tuple(d_out[0])
    except Exception as e:
        head["error"] = str(e)
        head["compatible"] = None
    out["head"] = head
    if head.get("compatible") is False:
        bad = True
    # would-be multi-token commit selection (ADVISORY by the same
    # contract as the single-row selection report)
    t_cache = [s.strip() for s in args.decode_cache.split(",")
               if s.strip()]
    d_cache = [s.strip() for s in args.draft_cache.split(",")
               if s.strip()]
    unshaped = [n for n in t_cache if n not in shapes] \
        + ["draft:" + n for n in d_cache if n not in dshapes]
    if unshaped:
        # a typo'd cache name must not silently shrink the audit to
        # an empty selection report ("the optimizer selects nothing"
        # is a conclusion, not a shrug)
        out["error"] = ("cache state(s) %s have no --shapes/"
                        "--draft-shapes entry" % unshaped)
        return out, True
    specs = [(n, tuple(shapes[n]), "float32") for n in t_cache]
    specs += [("draft:" + n, tuple(dshapes[n]), "float32")
              for n in d_cache]
    sels = []
    if specs:
        try:
            from mxnet_tpu.serving.spec import (build_commit_sym,
                                                select_commit)
            commit, cshapes, cn, rn = build_commit_sym(
                specs, args.spec_k + 1)
            # the SAME gated selection the engine runs (one
            # implementation, serving/spec.py)
            _served, _sel, plan = select_commit(commit, cshapes, cn,
                                                rn)
            v = "accepted" if plan.accepted \
                else "rejected: %s" % plan.reason
            sels = [{"op": "_cache_write_rows", "site": a.node,
                     "verdict": v, "detail": a.detail}
                    for a in plan.actions if a.kind == "select"]
        except Exception as e:
            sels = [{"op": None, "site": None,
                     "verdict": "error: %s" % e}]
    out["selections"] = sels
    return out, bad


def _print_draft_audit(audit):
    if audit is None:
        return
    if audit.get("error"):
        print("  draft audit FAILED: %s" % audit["error"])
        return
    print("  draft %s (k=%d): slot axis %s"
          % (audit["draft"], audit["k"],
             audit["draft_verdicts"]["slot"]))
    head = audit.get("head") or {}
    if head.get("compatible") is None:
        print("    head compatibility: unknown (%s)"
              % head.get("error"))
    else:
        print("    head compatibility: %s (target %s %s, draft %s %s%s)"
              % ("OK" if head["compatible"] else "FAIL",
                 head.get("target"), head.get("target_dtype"),
                 head.get("draft"), head.get("draft_dtype"),
                 "" if head.get("dtype_match")
                 else "; dtype differs — served with casts, advisory"))
    for s in audit.get("selections") or ():
        print("    would-be commit selection: %s at %s (%s)"
              % (s["op"], s["site"], s["verdict"]))


def _decode_selections(analysis, graph, shapes, state_names,
                       valid_name, training):
    """Report the fused-op selections (op, site, verdict) the
    optimizer's selection stage would make on a decode step graph —
    the offline audit of ``MXNET_OPT_SELECT_KERNELS`` kernel swaps.
    Advisory by contract: a crash or a rejected plan is itself part of
    the report, never an exit-code change."""
    try:
        plan = analysis.optimize_graph(
            graph, data_shapes=shapes,
            pad_axes={"slot": {n: 0 for n in shapes}},
            valid_lengths=({"slot": valid_name} if valid_name else None),
            pad_dirty=tuple(state_names), training=training,
            passes=analysis.SELECT_OPT_PASSES)
    except Exception as e:
        return [{"op": None, "site": None,
                 "verdict": "error: %s" % e}]
    verdict = "accepted" if plan.accepted \
        else "rejected: %s" % plan.reason
    return [{"op": "_cache_write_row", "site": a.node,
             "verdict": verdict, "detail": a.detail}
            for a in plan.actions if a.kind == "select"]


def _json_float(v):
    """Mask neutral elements include +/-inf, which json.dumps would
    emit as the RFC-8259-invalid ``-Infinity``; strict consumers (jq,
    JSON.parse) must still be able to read the document, so
    non-finite values serialize as strings ("-inf"/"inf"/"nan")."""
    if v is None or (v == v and float("-inf") < v < float("inf")):
        return v
    return str(v)


def _shape_valid_lengths(graph, shapes):
    """Auto-shape ``__pad_valid_len__``-marked inputs (the masks'
    driver in repaired graphs): a (batch,) vector sized off the first
    shaped input, so re-linting a ``--fix`` output needs no extra
    --shapes entry.  Returns (shapes, set of marked names)."""
    valid_vars = set()
    batch = next((s[0] for s in shapes.values() if s), None)
    from mxnet_tpu.symbol.symbol import _topo
    for n in _topo(graph._outputs):
        if n.op is None and n.attrs.get("__pad_valid_len__"):
            valid_vars.add(n.name)
            if n.name not in shapes and batch is not None:
                shapes[n.name] = (batch,)
    return shapes, valid_vars


def _out_dir(args, spec):
    """Artifact directory for --fix/--optimize emissions."""
    return args.fix_dir or (os.path.dirname(spec)
                            if os.path.sep in spec
                            or spec.endswith(".json") else ".")


def _optimize_graph_cli(analysis, spec, graph, shapes, pad_axes, policy,
                        args, entry, fix_lines, failed, precomputed=None):
    """--optimize: run the verdict-gated pass pipeline on the analyzed
    graph, record the plan (per-pass applied/rejected counts, node
    before/after, FLOP delta, fusion hints), and emit
    <stem>.optimized.json when rewrites were accepted.  A REJECTED plan
    fails the run even non-strict — it means the optimizer produced a
    verdict-worsening candidate, which is a pipeline bug, and CI must
    see it."""
    plan = analysis.optimize_graph(graph, data_shapes=shapes,
                                   policy=policy, pad_axes=pad_axes,
                                   training=args.training,
                                   precomputed=precomputed)
    entry["optimization"] = plan.to_dict()
    fix_lines.append(plan.describe())
    if not plan.accepted:
        return True
    if plan.rewrites:
        out_dir = _out_dir(args, spec)
        stem = os.path.splitext(os.path.basename(spec))[0] or spec
        out_path = os.path.join(out_dir or ".", stem + ".optimized.json")
        plan.symbol.save(out_path)
        entry["optimized_symbol"] = out_path
        fix_lines.append("  optimized symbol written to %s" % out_path)
    return failed


def _fix_graph(analysis, spec, graph, shapes, pad_axes, policy, args,
               passes, report, ctx, entry, fix_lines, failed, hard):
    """--fix: repair every cross-position label (seq first), emit the
    rewritten symbol JSON, and re-score the graph on a full re-lint of
    the repaired symbol when everything repaired."""
    cross = [lb for lb, v in sorted(ctx.pad_verdicts.items())
             if v == "cross-position"]
    cross.sort(key=lambda lb: lb != "seq")      # seq first
    if not cross:
        return failed, hard
    cur, all_ok, last_plan = graph, True, None
    pre = (report, ctx)         # the analysis main() just ran
    for label in cross:
        plan = analysis.plan_repair(cur, shapes, pad_axes, label=label,
                                    policy=policy,
                                    training=args.training,
                                    precomputed=pre)
        pre = None              # chained labels re-analyze the rewrite
        entry["repairs"].append({
            "label": label, "accepted": plan.accepted,
            "reason": plan.reason,
            "valid_length_input": plan.valid_length_name,
            "actions": [{"node": a.node, "op": a.op, "kind": a.kind,
                         "value": _json_float(a.value),
                         "axes": list(a.axes),
                         "slot": a.slot} for a in plan.actions]})
        fix_lines.append(plan.describe())
        if not plan.accepted:
            # the user asked for a repair and it could not be done:
            # that fails the run even without --strict (the documented
            # "rejected --fix exits 1" contract)
            all_ok = False
            failed = True
            continue
        cur, last_plan = plan.symbol, plan
        shapes = dict(shapes)
        bname, bax = next(iter(pad_axes["batch"].items()))
        shapes[plan.valid_length_name] = (shapes[bname][bax],)
        pad_axes = {lb: dict(m) for lb, m in pad_axes.items()}
        pad_axes["batch"][plan.valid_length_name] = 0
    if last_plan is not None:
        out_dir = _out_dir(args, spec)
        stem = os.path.splitext(os.path.basename(spec))[0] or spec
        # a partially-repaired graph (some labels' repairs rejected —
        # it is STILL cross-position along those) must not be
        # confusable with a fully-repaired artifact: distinct suffix,
        # distinct report key, and the exit code keeps failing
        suffix = ".repaired.json" if all_ok else ".repaired.partial.json"
        out_path = os.path.join(out_dir or ".", stem + suffix)
        cur.save(out_path)
        entry["repaired_symbol" if all_ok else
              "partial_symbol"] = out_path
        fix_lines.append("  %s symbol written to %s"
                         % ("repaired" if all_ok else
                            "PARTIALLY repaired (still unsound along "
                            "the rejected axes)", out_path))
        if all_ok:
            # the graph the user will serve is the repaired one: score
            # a FULL re-lint of it under the same pass selection —
            # plan_repair's internal re-verification only ran
            # verify+shapes+padding, and e.g. a retrace-linter warning
            # must keep failing the --strict bar after a repair too
            report2, ctx2 = analysis.analyze(
                cur, data_shapes=shapes, policy=policy,
                pad_axes=pad_axes, training=args.training, passes=passes)
            failed = not report2.clean(strict=args.strict)
            hard = bool(report2.errors)
            entry["repaired_findings"] = report2.to_list()
            # --json consumers join on verdicts: the graph that passes
            # is the repaired one, so record ITS per-axis verdicts
            # alongside the original's
            entry["repaired_verdicts"] = dict(ctx2.pad_verdicts)
    return failed, hard


if __name__ == "__main__":
    sys.exit(main())
