"""serving.locks — named-lock wrappers + the runtime lock sanitizer.

The serving tier's documented home for the lock-discipline API; the
implementation lives in :mod:`mxnet_tpu.locks` (package top level,
stdlib-only imports) so that telemetry/ — which serving imports — can
adopt the same named locks without an import cycle.

Usage (the runtime's own pattern)::

    from .locks import named_lock, named_condition       # serving/
    from ..locks import named_lock                       # telemetry/

    self._route_lock = named_lock("serve.route")
    self._route_cond = named_condition("serve.route", self._route_lock)

With ``MXNET_LOCK_SANITIZER=0`` (default) these ARE the plain
``threading`` primitives; with ``=1`` they record acquisition-order
edges, held-sets, and hold-time histograms.  See
:mod:`mxnet_tpu.locks` and the README "Concurrency soundness" section.
"""
from ..locks import (named_lock, named_rlock, named_condition, enabled,
                     enable, disable, reset, observed_edges, hold_stats,
                     observed_inversions, assert_no_inversions, stats,
                     dump, HOLD_BUCKETS, LockInversionError)

__all__ = ["named_lock", "named_rlock", "named_condition", "enabled",
           "enable", "disable", "reset", "observed_edges", "hold_stats",
           "observed_inversions", "assert_no_inversions", "stats",
           "dump", "HOLD_BUCKETS", "LockInversionError"]
