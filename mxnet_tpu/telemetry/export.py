"""Exporters: Prometheus text exposition, JSON snapshots, periodic dump.

Two machine formats over one ``Registry.collect()`` snapshot:

- ``render_prometheus`` — the text exposition format scrapers ingest
  (``# HELP``/``# TYPE`` headers, cumulative ``_bucket{le=...}``
  histogram series ending in ``+Inf``, ``_sum``/``_count``);
- ``render_json`` — the collect() dict plus the finished-trace store,
  the self-contained document ``tools/telemetry_dump.py`` renders
  offline.

Plus a **snapshot thread**: serving processes run for days with nobody
attached, so a daemon thread periodically writes the current snapshot
to a file (atomic replace — a scraper/tailer never sees a torn write)
or stdout.  Configured by ``MXNET_TELEMETRY_SNAPSHOT_SECS`` / ``_PATH``
/ ``_FORMAT``; started lazily at first telemetry import and stoppable
for tests.
"""
from __future__ import annotations

import itertools
import json
import os
import re
import sys
import threading

from ..base import MXNetError

_TMP_SEQ = itertools.count()

__all__ = ["render_prometheus", "render_json", "write_snapshot",
           "start_snapshotter", "stop_snapshotter",
           "start_rank_snapshotter", "lint_metric_names",
           "METRIC_NAME_RE"]

# every metric this stack exposes must live in the mxnet_ namespace —
# the exporter/docs drift gate (tests lint the live /metrics output
# against this)
METRIC_NAME_RE = re.compile(r"^mxnet_[a-z0-9_]+$")


def _esc(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _labelstr(labels, extra=None):
    items = list(labels.items()) + (list(extra.items()) if extra else [])
    if not items:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _esc(v)) for k, v in items)


def _num(v):
    if v != v:                                   # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry=None):
    """Registry snapshot in the Prometheus text exposition format."""
    if registry is None:
        from . import registry as _default
        registry = _default()
    doc = registry.collect()
    lines = []
    for name in sorted(doc):
        fam = doc[name]
        if fam["doc"]:
            lines.append("# HELP %s %s" % (name, _esc(fam["doc"])))
        lines.append("# TYPE %s %s" % (name, fam["kind"]))
        for s in fam["series"]:
            if fam["kind"] == "histogram":
                acc = 0
                for le, c in zip(s["buckets"], s["counts"]):
                    acc += c
                    lines.append("%s_bucket%s %d" % (
                        name, _labelstr(s["labels"], {"le": _num(le)}),
                        acc))
                acc += s["counts"][-1]
                lines.append("%s_bucket%s %d" % (
                    name, _labelstr(s["labels"], {"le": "+Inf"}), acc))
                lines.append("%s_sum%s %s" % (
                    name, _labelstr(s["labels"]), _num(s["sum"])))
                lines.append("%s_count%s %d" % (
                    name, _labelstr(s["labels"]), s["count"]))
            else:
                lines.append("%s%s %s" % (
                    name, _labelstr(s["labels"]), _num(s["value"])))
    return "\n".join(lines) + "\n"


def _finite(obj):
    """Map non-finite floats to null: RFC 8259 JSON has no NaN/Infinity
    tokens, and a diverging model publishing a NaN gauge must not make
    the whole snapshot unparseable to strict consumers (jq,
    JSON.parse) during exactly the incident being debugged."""
    if isinstance(obj, float):
        import math
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_finite(v) for v in obj]
    return obj


def render_json(registry=None, include_traces=True, meta=None):
    """Self-contained JSON document: metrics snapshot + finished
    traces.  This is the format ``tools/telemetry_dump.py`` consumes.
    ``meta`` merges extra top-level keys into the document — the rank
    snapshotter stamps ``{"rank": N}`` so cross-host aggregation can
    label each series with its source."""
    if registry is None:
        from . import registry as _default
        registry = _default()
    import time
    # scrape_ts (wall clock) + scrape_monotonic stamp WHEN the snapshot
    # was rendered: N rank snapshots in a shared dir were previously
    # unorderable (each carried only its own uptime), so `telemetry_dump
    # aggregate` could silently merge a fresh rank with a stale one —
    # it now warns on >60 s wall-clock skew between ranks.
    doc = {"format": "mxnet_tpu.telemetry/1",
           "scrape_ts": time.time(),
           "scrape_monotonic": time.monotonic(),
           "metrics": registry.collect()}
    if include_traces:
        from . import tracing
        doc["traces"] = tracing.all_traces()
    from . import timeline
    if timeline.enabled():
        # the fleet-event window rides every JSON snapshot: rank
        # documents under MXNET_TELEMETRY_SHARED_DIR therefore carry
        # the events `telemetry_dump aggregate/timeline` wall-aligns
        # across ranks on the scrape stamps above
        doc["timeline"] = timeline.get().snapshot(limit=8192)
    if meta:
        doc.update(meta)
    return json.dumps(_finite(doc), indent=1, sort_keys=True,
                      allow_nan=False)


def write_snapshot(path=None, fmt=None, registry=None, meta=None):
    """Write one snapshot now.  ``path=None``/empty writes to stdout.
    Returns the rendered text.  File writes go through a same-directory
    temp file + ``os.replace`` so readers never observe a torn
    snapshot."""
    if fmt is None:
        from .. import config
        fmt = config.get("MXNET_TELEMETRY_SNAPSHOT_FORMAT")
    if fmt == "prom":
        text = render_prometheus(registry)
    elif fmt == "json":
        text = render_json(registry, meta=meta)
    else:
        raise MXNetError("unknown telemetry snapshot format %r "
                         "(use 'prom' or 'json')" % (fmt,))
    if not path:
        sys.stdout.write(text)
        return text
    # unique per writer: the snapshot thread and a concurrent
    # dump_state()/atexit write to the same path must not share a temp
    # file, or os.replace could publish interleaved (torn) content
    tmp = "%s.tmp.%d.%d.%d" % (path, os.getpid(),
                               threading.get_ident(), next(_TMP_SEQ))
    try:
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        # the snapshot thread retries forever with fresh names — a
        # failed write (disk full) must not strand one tmp per tick
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return text


class _Snapshotter(object):
    def __init__(self, interval_s, path, fmt, registry=None, meta=None):
        self.interval_s = float(interval_s)
        self.path = path
        self.fmt = fmt
        self.registry = registry
        self.meta = meta
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="mxnet-telemetry-snapshot",
                                        daemon=True)
        self._thread.start()

    def _write(self):
        write_snapshot(self.path, self.fmt, registry=self.registry,
                       meta=self.meta)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self._write()
            except Exception:
                pass        # a failed write must never kill the thread

    def stop(self, final=True):
        self._stop.set()
        self._thread.join(timeout=5)
        if final:
            try:
                self._write()
            except Exception:
                pass


_SNAPSHOTTER = None
_SNAP_LOCK = threading.Lock()


def start_snapshotter(interval_s=None, path=None, fmt=None):
    """Start (or replace) the periodic snapshot thread.  Defaults come
    from the MXNET_TELEMETRY_SNAPSHOT_* env tier; ``interval_s`` <= 0
    is a no-op returning None."""
    global _SNAPSHOTTER
    from .. import config
    if interval_s is None:
        interval_s = config.get("MXNET_TELEMETRY_SNAPSHOT_SECS")
    if path is None:
        path = config.get("MXNET_TELEMETRY_SNAPSHOT_PATH") or None
    if fmt is None:
        fmt = config.get("MXNET_TELEMETRY_SNAPSHOT_FORMAT")
    if fmt not in ("prom", "json"):
        # fail fast HERE: the thread swallows per-tick errors (a full
        # disk must not kill it), so a typo'd format would otherwise
        # write nothing, silently, for the life of the process
        raise MXNetError("unknown telemetry snapshot format %r "
                         "(use 'prom' or 'json')" % (fmt,))
    if not interval_s or interval_s <= 0:
        return None
    with _SNAP_LOCK:
        if _SNAPSHOTTER is not None:
            _SNAPSHOTTER.stop(final=False)
        _SNAPSHOTTER = _Snapshotter(interval_s, path, fmt)
        return _SNAPSHOTTER


def stop_snapshotter(final=True):
    """Stop the periodic snapshot thread (writing one last snapshot by
    default)."""
    global _SNAPSHOTTER
    with _SNAP_LOCK:
        if _SNAPSHOTTER is not None:
            _SNAPSHOTTER.stop(final=final)
            _SNAPSHOTTER = None


# -- cross-host aggregation: rank-tagged snapshots --------------------------

_RANK_SNAPSHOTTERS = {}      # path -> _Snapshotter (replace on re-start)


def start_rank_snapshotter(shared_dir, rank, interval_s=None,
                           registry=None):
    """Periodically write THIS process's registry snapshot as a
    rank-tagged JSON file under ``shared_dir`` — the dist-kvstore tier
    publishing into one place so ``tools/telemetry_dump.py aggregate``
    can join N ranks into a single document.

    The file is ``telemetry_rank<rank>.json`` (atomic replace, same as
    every snapshot write) and the document carries a top-level
    ``rank`` key, so aggregation never has to guess from filenames.
    One snapshot is written immediately (short jobs must leave a
    record); ``interval_s`` defaults to MXNET_TELEMETRY_SNAPSHOT_SECS,
    falling back to 30 s when that is 0 (the shared-dir push being
    requested at all implies somebody wants the data).  Returns a
    handle with ``.stop()`` (writes one final snapshot).
    """
    from .. import config
    os.makedirs(shared_dir, exist_ok=True)
    path = os.path.join(shared_dir, "telemetry_rank%d.json" % int(rank))
    meta = {"rank": int(rank)}
    write_snapshot(path, "json", registry, meta=meta)
    if interval_s is None:
        interval_s = config.get("MXNET_TELEMETRY_SNAPSHOT_SECS") or 30.0
    with _SNAP_LOCK:
        old = _RANK_SNAPSHOTTERS.pop(path, None)
        if old is not None:
            old.stop(final=False)
        snap = _Snapshotter(interval_s, path, "json", registry=registry,
                            meta=meta)
        _RANK_SNAPSHOTTERS[path] = snap
    return snap


# -- exporter/docs drift gate -----------------------------------------------

def lint_metric_names(text=None, registry=None):
    """Return every metric family name in a Prometheus exposition that
    does NOT match ``^mxnet_[a-z0-9_]+$`` — the namespace contract the
    docs promise.  ``text`` defaults to rendering ``registry`` (default
    registry), i.e. exactly what ``GET /metrics`` would serve; CI runs
    this over a live scrape so exporter and docs cannot drift."""
    if text is None:
        text = render_prometheus(registry)
    bad = []
    for line in text.splitlines():
        m = re.match(r"# TYPE (\S+) ", line)
        if m and not METRIC_NAME_RE.match(m.group(1)):
            bad.append(m.group(1))
    return bad
