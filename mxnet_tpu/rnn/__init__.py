"""mx.rnn — symbolic RNN toolkit (reference python/mxnet/rnn/)."""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, DropoutCell, ModifierCell,
                       ResidualCell, ZoneoutCell, BidirectionalCell,
                       FusedRNNCell)
from .io import encode_sentences, BucketSentenceIter
from .rnn import (save_rnn_checkpoint, load_rnn_checkpoint,
                  do_rnn_checkpoint)

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ResidualCell", "ZoneoutCell", "BidirectionalCell",
           "FusedRNNCell", "encode_sentences", "BucketSentenceIter",
           "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]
