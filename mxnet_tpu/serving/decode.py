"""Continuous batching for autoregressive decode — iteration-level
scheduling over a persistent slot pool.

The one-shot engine (engine.py) coalesces requests into a batch,
dispatches ONCE, and scatters results.  Sequence models cannot be
served that way without catastrophic waste: a static batch holds every
finished sequence hostage until the slowest member completes, and new
requests wait for the whole batch to drain.  This module schedules at
the *iteration* level instead (ROADMAP item 1 — THE millions-of-users
workload):

- **one persistent step program** compiled ONCE over a fixed-capacity
  slot pool (``MXNET_DECODE_SLOTS`` slots x ``MXNET_DECODE_MAX_LEN``
  positions).  Requests join and leave the running batch BETWEEN steps
  with zero retraces — shapes never change, so the jit cache is never
  busted (the compile counter is pinned across churn by tests);
- **device-resident per-slot state**: recurrent state (h/c per
  :meth:`~mxnet_tpu.rnn.rnn_cell.BaseRNNCell.begin_state_arrays`) or a
  fixed-layout KV cache in the O(1)-per-token mold of PAPERS.md
  "Compiler-First State Space Duality and Portable O(1) Autoregressive
  Caching" (arxiv 2603.09555): a ``(slots, max_len, d)`` buffer
  written at one position per step, never grown, never re-laid-out.
  State stays in HBM across steps (buffers are donated to the step
  dispatch off-CPU); the host ships only the per-step new-token id
  vector and the slot-occupancy/valid vector, and receives only the
  sampled token ids back;
- **masked dead slots**: free slots ride along in every dispatch
  holding whatever a finished request left behind.  That is sound
  exactly when the step graph is row-local along the slot axis —
  :func:`mxnet_tpu.analysis.check_decode_step` proves it at
  construction with the same padding classifier serving already
  trusts, seeding state inputs pad-DIRTY so stale garbage gets no
  zero-absorption credit (``tools/graph_lint.py --decode-step`` runs
  the same lint offline);
- **bucketed prefill**: a prompt is consumed either token-by-token
  through the running step batch (teacher forcing — no extra
  programs), or, with a ``prefill_sym``, in ONE dispatch through the
  existing :class:`~mxnet_tpu.serving.buckets.ProgramCache` at pow2
  seq buckets, its output state scattered into the free slot;
- **admission + per-step deadlines**: the same
  :class:`~mxnet_tpu.serving.admission.AdmissionController` front door
  (bounded queue, reject/shed overload policies); deadlines are
  re-checked every iteration, and an expired request — queued or
  mid-generation — completes with its PARTIAL output and the
  ``expired`` flag instead of failing (``Request.on_expire``).

Quick start::

    eng = serving.DecodeEngine(step_sym, params, {}, state_info=[
        {"name": "h", "shape": (H,)}, {"name": "c", "shape": (H,)}])
    eng.warmup()
    fut = eng.submit([bos_id], max_new_tokens=32)
    res = fut.result()          # DecodeResult: tokens, finish_reason
    eng.close()

Step-graph contract: ``step_sym`` outputs ``[logits] + next_states``
(exactly like ``BaseRNNCell.__call__``), over arguments ``token``
(slot vector of last token ids), the state names from ``state_info``
(each ``(slots,) + per_slot_shape``), and optionally ``pos`` (per-slot
write position) and ``valid`` (1/0 occupancy).  The engine appends a
greedy ``argmax`` head so only token ids cross the host boundary.
"""
from __future__ import annotations

import collections
import threading
import time
import warnings
import weakref
from concurrent.futures import Future

import numpy as np

from ..base import MXNetError
from .. import telemetry as _telemetry
from .admission import (AdmissionController, Request, EngineClosedError,
                        _fail_future)
from .buckets import ProgramCache, _next_pow2
from .engine import _ENGINE_SEQ, _percentile

__all__ = ["DecodeEngine", "DecodeResult", "StepProgram", "greedy_decode"]


class DecodeResult(object):
    """What a decode future resolves to: the generated token ids plus
    how generation ended.

    ``finish_reason`` is one of ``"eos"`` (the eos id was sampled),
    ``"length"`` (max_new_tokens or the slot's max_len capacity),
    ``"deadline"`` (the request's deadline passed mid-flight — tokens
    holds the PARTIAL generation), or ``"closed"`` (engine shut down
    without drain).  ``expired`` mirrors the deadline case.
    """
    __slots__ = ("tokens", "finish_reason", "n_steps", "prompt_len")

    def __init__(self, tokens, finish_reason, n_steps=0, prompt_len=0):
        self.tokens = np.asarray(tokens, dtype=np.int64)
        self.finish_reason = finish_reason
        self.n_steps = n_steps
        self.prompt_len = prompt_len

    @property
    def expired(self):
        return self.finish_reason == "deadline"

    def __len__(self):
        return len(self.tokens)

    def __repr__(self):
        return ("<DecodeResult %d tokens, %s>"
                % (len(self.tokens), self.finish_reason))


class DecodeRequest(Request):
    """One decode request: a prompt plus generation bookkeeping the
    scheduler mutates as the request moves queue -> slot -> done."""
    __slots__ = ("prompt", "max_new", "tokens", "prompt_i", "slot",
                 "t_join", "n_steps", "t_first_tok", "t_last_tok")

    def __init__(self, prompt, max_new, future, deadline=None,
                 trace=None):
        super().__init__({}, ("__decode__",), future, deadline=deadline,
                         trace=trace)
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.tokens = []            # generated ids (host mirror)
        self.prompt_i = 0           # next prompt token to teacher-force
        self.slot = None
        self.t_join = None
        self.n_steps = 0
        # decode latency anatomy: first/last generated-token stamps
        # feed the TTFT and inter-token (TPOT) histograms
        self.t_first_tok = None
        self.t_last_tok = None


class StepProgram(object):
    """The persistent compiled decode step over a fixed slot pool.

    Wraps ``step_sym`` (outputs ``[logits] + next_states``) with a
    greedy ``argmax`` head and compiles it ONCE at batch extent
    ``num_slots`` — iteration-level scheduling never changes a shape,
    so ``trace_count`` is the whole compile story: the step kernel,
    plus one tiny row-write kernel per distinct state shape (slot
    join/leave scatter), all exercised by ``DecodeEngine.warmup``.

    Per-slot state lives as jax device buffers between calls; on
    non-CPU backends the state arguments are DONATED to the dispatch,
    so the pool is updated in place in HBM (the O(1) cache layout of
    arxiv 2603.09555 — no growth, no re-layout, no host round-trip).
    """

    def __init__(self, step_sym, arg_params, aux_params, state_info,
                 num_slots, token_name="token", pos_name="pos",
                 valid_name="valid", ctx=None, dtype=np.float32):
        import jax
        import jax.numpy as jnp
        from ..context import cpu
        from ..executor import build_graph_fn, _count_xla_trace
        from .. import symbol as sym
        self._ctx = ctx or cpu()
        self.num_slots = int(num_slots)
        self._dtype = np.dtype(dtype)
        self.state_info = [dict(s) for s in state_info]
        self.state_names = [s["name"] for s in self.state_info]
        self.token_name = token_name
        if len(step_sym) != 1 + len(self.state_names):
            raise MXNetError(
                "decode step graph has %d outputs; expected 1 (logits) "
                "+ %d next-state outputs (state_info order)"
                % (len(step_sym), len(self.state_names)))
        sampled = sym.argmax(step_sym[0], axis=1,
                             name="__decode_sample__")
        self._serve_sym = sym.Group(
            [sampled] + [step_sym[i]
                         for i in range(1, len(step_sym))])
        arg_names = self._serve_sym.list_arguments()
        aux_names = self._serve_sym.list_auxiliary_states()
        if token_name not in arg_names:
            raise MXNetError("decode step graph has no %r input "
                             "(token_name); arguments: %s"
                             % (token_name, arg_names))
        missing = [n for n in self.state_names if n not in arg_names]
        if missing:
            raise MXNetError("decode step graph is missing state "
                             "input(s) %s" % missing)
        self.pos_name = pos_name if pos_name in arg_names else None
        self.valid_name = valid_name if valid_name in arg_names else None
        feeds = set([token_name] + self.state_names)
        feeds.update(n for n in (self.pos_name, self.valid_name) if n)
        lacking = [n for n in arg_names
                   if n not in feeds and n not in (arg_params or {})]
        if lacking:
            raise MXNetError("StepProgram: params missing for %s"
                             % lacking)
        order = list(arg_names) + list(aux_names)
        self._template = [None] * len(order)
        for i, n in enumerate(order):
            if n in feeds:
                continue
            src = arg_params if n in (arg_params or {}) else aux_params
            self._template[i] = src[n].as_in_context(self._ctx)._data
        self._feed_pos = {n: order.index(n) for n in feeds}
        gf = build_graph_fn(self._serve_sym, arg_names, aux_names)
        if gf.stochastic:
            raise MXNetError(
                "decode step graph contains stochastic ops (Dropout, "
                "samplers): the persistent step must be deterministic "
                "— greedy decode parity and per-slot bitwise "
                "reproducibility both depend on it")
        self._trace_count = 0
        na = len(arg_names)
        state_pos = tuple(order.index(n) for n in self.state_names)

        def call(key, reset, *flat):
            self._trace_count += 1      # runs once per XLA trace
            _count_xla_trace()
            # a joining slot's state is zeroed HERE, fused into the
            # step program (``reset`` is a per-slot 1/0 host vector):
            # a join costs no device dispatch of its own, unlike a
            # write_row scatter (~ms each on CPU jax) per join.
            # jnp.where, not multiply: stale rows may hold non-finite
            # values and 0*inf would leak NaN into the fresh state.
            flat = list(flat)
            for i in state_pos:
                s = flat[i]
                r = reset.reshape((-1,) + (1,) * (s.ndim - 1))
                flat[i] = jnp.where(r > 0, jnp.zeros((), s.dtype), s)
            outs, _ = gf(flat[:na], flat[na:], key, False)
            return outs

        donate = ()
        if jax.default_backend() != "cpu":
            # in-place HBM update of the slot pool: the old state
            # buffers are donated to the dispatch (CPU jax cannot
            # honor donation and would warn per compile)
            donate = tuple(2 + order.index(n) for n in self.state_names)
        self._kernel = jax.jit(call, donate_argnums=donate)
        from .. import random as _random
        self._key = _random.next_key()     # dead input: deterministic

        def set_row(buf, idx, row):
            self._trace_count += 1
            _count_xla_trace()
            return buf.at[idx].set(row)

        # one trace per distinct state shape; the slot index is a
        # traced scalar so churn across slots never retraces
        self._set_row = jax.jit(set_row)
        self._jnp = jnp

    @property
    def trace_count(self):
        return self._trace_count

    def init_states(self):
        """Fresh all-zero slot-pool state buffers (device)."""
        out = {}
        for info in self.state_info:
            dt = np.dtype(info.get("dtype") or self._dtype)
            out[info["name"]] = self._jnp.zeros(
                (self.num_slots,) + tuple(info["shape"]), dtype=dt)
        return out

    def write_row(self, states, slot, rows):
        """Scatter per-slot state rows (host or device arrays) into
        ``slot`` of every buffer named in ``rows``; returns the updated
        state dict.  The index is passed as a traced scalar — one
        compile per state shape, ever."""
        idx = self._jnp.asarray(slot, self._jnp.int32)
        out = dict(states)
        for name, row in rows.items():
            out[name] = self._set_row(out[name], idx, row)
        return out

    def zero_row(self, states, slot):
        """Zero one slot's rows in every state buffer (a joining
        request must never inherit the previous occupant's state)."""
        rows = {}
        for info in self.state_info:
            dt = np.dtype(info.get("dtype") or self._dtype)
            rows[info["name"]] = np.zeros(tuple(info["shape"]), dtype=dt)
        return self.write_row(states, slot, rows)

    def step(self, tokens, pos, valid, states, reset=None):
        """One decode iteration over the whole pool.  ``tokens``/
        ``pos``/``valid`` are host float32 vectors of length
        ``num_slots``; ``states`` the device buffers from
        :meth:`init_states`/previous steps.  ``reset`` optionally
        marks slots (1/0) whose state rows must read as fresh zeros
        this step — how a join clears the previous occupant's rows
        without a single extra device dispatch.  Returns (sampled ids
        as a host float vector, new state dict) — the only
        device->host traffic is the id vector."""
        if reset is None:
            reset = np.zeros((self.num_slots,), np.float32)
        flat = list(self._template)
        flat[self._feed_pos[self.token_name]] = tokens
        if self.pos_name is not None:
            flat[self._feed_pos[self.pos_name]] = pos
        if self.valid_name is not None:
            flat[self._feed_pos[self.valid_name]] = valid
        for name in self.state_names:
            flat[self._feed_pos[name]] = states[name]
        outs = self._kernel(self._key, reset, *flat)
        new_states = {name: outs[1 + i]
                      for i, name in enumerate(self.state_names)}
        return np.asarray(outs[0]), new_states


def greedy_decode(program, prompt, max_new_tokens, eos_id=None,
                  max_len=None):
    """Reference single-request greedy decode: teacher-force the prompt
    through ``program`` one token per step, then feed each argmax
    sample back, alone in slot 0.  This is the bitwise ground truth
    the continuous-batching engine is held to (tests/test_decode.py):
    whatever company a request keeps in the slot pool, its tokens must
    equal this loop's output exactly."""
    states = program.init_states()
    n = program.num_slots
    tokens = np.zeros((n,), np.float32)
    pos = np.zeros((n,), np.float32)
    valid = np.zeros((n,), np.float32)
    valid[0] = 1.0
    prompt = list(prompt)
    if not prompt:
        raise MXNetError("greedy_decode needs a non-empty prompt")
    tokens[0] = prompt[0]
    out, p, i = [], 0, 1
    while len(out) < max_new_tokens:
        if max_len is not None and p >= max_len:
            break
        pos[0] = p
        sampled, states = program.step(tokens, pos, valid, states)
        p += 1
        if i < len(prompt):             # still consuming the prompt
            tokens[0] = prompt[i]
            i += 1
            continue
        tok = int(sampled[0])
        out.append(tok)
        tokens[0] = sampled[0]
        if eos_id is not None and tok == eos_id:
            break
    return np.asarray(out, dtype=np.int64)


class _DecodeTelemetry(object):
    """Decode engine's instrument bundle (mxnet_serve_decode_*), built
    only when telemetry is enabled.  Shares the admission families
    with the one-shot engine (AdmissionController reads ``admitted``/
    ``rejected``/``shed``/``expired``/``queue_depth`` off this object)
    so both engine kinds aggregate into one serving picture; decode-
    specific series follow the PR 3-7 idiom — shared counters, per-
    engine gauges reclaimed at close()."""

    def __init__(self, engine):
        reg = _telemetry.registry()
        self.engine_label = str(next(_ENGINE_SEQ))
        self.closed = False
        self.requests = reg.counter(
            "mxnet_serve_requests_total", "serving requests submitted")
        self.admitted = reg.counter(
            "mxnet_serve_admitted_total", "requests admitted")
        self.rejected = reg.counter(
            "mxnet_serve_rejected_total",
            "requests rejected with QueueFullError backpressure")
        self.shed = reg.counter(
            "mxnet_serve_shed_total",
            "requests shed under the shed-oldest overload policy")
        self.expired = reg.counter(
            "mxnet_serve_expired_total",
            "requests expired past their deadline while queued")
        queue_depth_fam = reg.gauge(
            "mxnet_serve_queue_depth",
            "pending admission-queue depth per engine",
            labelnames=("engine",))
        self.queue_depth = queue_depth_fam.labels(
            engine=self.engine_label)
        self.tokens = reg.counter(
            "mxnet_serve_decode_tokens_total",
            "tokens generated by continuous-batching decode engines")
        self.steps = reg.counter(
            "mxnet_serve_decode_steps_total",
            "decode step-program dispatches (each steps every live "
            "slot once)")
        self.joins = reg.counter(
            "mxnet_serve_decode_joins_total",
            "requests that joined the running decode batch (slot "
            "assigned between steps — never a retrace)")
        self.leaves = reg.counter(
            "mxnet_serve_decode_leaves_total",
            "requests that left the decode batch, by how generation "
            "ended (eos / length / deadline / closed / cancelled)",
            labelnames=("reason",))
        # label handles resolved ONCE: .labels() does registry work
        # per call, and leaves are hot-path (one per finished request)
        self._leave = {r: self.leaves.labels(reason=r)
                       for r in ("eos", "length", "deadline", "closed",
                                 "cancelled")}
        self.evictions = reg.counter(
            "mxnet_serve_decode_evictions_total",
            "slot-resident requests evicted mid-generation by their "
            "deadline: the future resolves with the PARTIAL tokens "
            "and expired=True, and the slot frees for queued work")
        self.step_ms = reg.histogram(
            "mxnet_serve_decode_step_ms",
            "wall time of one decode iteration (deadline sweep + step "
            "dispatch + host bookkeeping)",
            buckets=_telemetry.LATENCY_MS_BUCKETS)
        # per-request tail latency the tokens/s counter cannot see
        # (the 2603.09555 O(1)-per-token framing is throughput-only):
        # TTFT = submit -> first generated token (queue wait + prefill
        # + first step), TPOT = mean inter-token gap over a finished
        # request's generation.  Engine-labeled so co-resident engines
        # keep distinct tails AND the series reclaim at close().
        ttft_fam = reg.histogram(
            "mxnet_serve_decode_ttft_seconds",
            "time to first token: submit -> first generated token id "
            "(queue wait + prefill + first step), per decode engine",
            labelnames=("engine",),
            buckets=_telemetry.LATENCY_S_BUCKETS)
        self.ttft = ttft_fam.labels(engine=self.engine_label)
        tpot_fam = reg.histogram(
            "mxnet_serve_decode_tpot_seconds",
            "inter-token latency: mean gap between consecutive "
            "generated tokens per finished request (>= 2 tokens), per "
            "decode engine",
            labelnames=("engine",),
            buckets=_telemetry.LATENCY_S_BUCKETS)
        self.tpot = tpot_fam.labels(engine=self.engine_label)
        slots_fam = reg.gauge(
            "mxnet_serve_decode_slots",
            "slot-pool capacity per decode engine",
            labelnames=("engine",))
        self.slots = slots_fam.labels(engine=self.engine_label)
        occupied_fam = reg.gauge(
            "mxnet_serve_decode_slots_occupied",
            "slots currently generating per decode engine — "
            "occupied/capacity is decode's batch-occupancy analog",
            labelnames=("engine",))
        self.occupied = occupied_fam.labels(engine=self.engine_label)
        compile_fam = reg.gauge(
            "mxnet_serve_compile_count",
            "CachedOp trace counter — programs compiled so far, per "
            "engine", labelnames=("engine",))
        self.compile_count = compile_fam.labels(
            engine=self.engine_label)
        self._engine_gauge_fams = (queue_depth_fam, slots_fam,
                                   occupied_fam, compile_fam,
                                   ttft_fam, tpot_fam)
        self._engine = weakref.ref(engine)
        reg.register_callback(self._refresh)

    def leave(self, reason):
        handle = self._leave.get(reason)
        (handle if handle is not None
         else self.leaves.labels(reason=reason)).inc()

    def close(self):
        self.closed = True
        _telemetry.registry().unregister_callback(self._refresh)
        self._remove_engine_series()

    def _remove_engine_series(self):
        for fam in self._engine_gauge_fams:
            fam.remove(engine=self.engine_label)

    def _refresh(self, reg):
        eng = self._engine()
        if eng is None:
            reg.unregister_callback(self._refresh)
            self._remove_engine_series()
            return
        self.slots.set(eng.num_slots)
        self.occupied.set(eng._occupied_count())
        self.compile_count.set(eng.compile_count)


class DecodeEngine(object):
    """Continuous-batching autoregressive decode over one frozen step
    graph (module docstring has the architecture).

    Parameters
    ----------
    step_sym : Symbol with outputs ``[logits] + next_states``.
    arg_params, aux_params : trained weights (checkpoint artifacts).
    state_info : list of ``{"name", "shape"[, "dtype"]}`` — per-slot
        state buffers, in the order the step graph returns their next
        values (``BaseRNNCell.state_info`` shapes with the batch dim
        dropped; see ``begin_state_arrays`` for the cell-side analog).
    num_slots, max_len : slot-pool geometry (defaults from
        ``MXNET_DECODE_SLOTS`` / ``MXNET_DECODE_MAX_LEN``).
    eos_id : sampling this id ends a request with reason "eos".
    prefill_sym : optional prompt-consumption graph with outputs
        ``[logits_at_last_valid_position] + state_rows`` over arguments
        ``prefill_data_name`` ((1, T) prompt ids, T padded onto pow2
        buckets) and ``prefill_len_name`` ((1,) live prompt length the
        graph's masking keys on).  Either a length-polymorphic Symbol
        or a callable ``T -> Symbol`` (the BucketingModule idiom — an
        unrolled graph bakes its length in).  Compiled through the
        one-shot bucket path (ProgramCache, one program per pow2
        bucket); its state rows are scattered into the free slot.
        Without it, prompts are teacher-forced token-by-token through
        the running step batch (no extra programs).
    """

    def __init__(self, step_sym, arg_params, aux_params, state_info,
                 token_name="token", pos_name="pos", valid_name="valid",
                 num_slots=None, max_len=None, eos_id=None,
                 prefill_sym=None, prefill_data_name="prompt",
                 prefill_len_name="plen",
                 max_queue=None, default_deadline_ms=None,
                 overload_policy=None, ctx=None, dtype=np.float32,
                 start=True):
        from .. import config
        if num_slots is None:
            num_slots = config.get("MXNET_DECODE_SLOTS")
        if max_len is None:
            max_len = config.get("MXNET_DECODE_MAX_LEN")
        if max_queue is None:
            max_queue = config.get("MXNET_SERVE_MAX_QUEUE")
        if default_deadline_ms is None:
            default_deadline_ms = config.get(
                "MXNET_SERVE_DEFAULT_DEADLINE_MS")
        if overload_policy is None:
            overload_policy = config.get("MXNET_SERVE_OVERLOAD_POLICY")
        if num_slots < 1:
            raise MXNetError("num_slots must be >= 1, got %d" % num_slots)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self._dtype = np.dtype(dtype)
        self._default_deadline_s = float(default_deadline_ms) / 1e3
        self.analysis_report = None
        self.step_verdict = None
        if config.get("MXNET_ANALYSIS_ON"):
            self._preflight(step_sym, state_info, token_name, pos_name,
                            valid_name, config.get("MXNET_ANALYSIS_STRICT"))
        self._program = StepProgram(step_sym, arg_params, aux_params,
                                    state_info, self.num_slots,
                                    token_name=token_name,
                                    pos_name=pos_name,
                                    valid_name=valid_name,
                                    ctx=ctx, dtype=dtype)
        # prefill through the one-shot bucket path: one compiled
        # program per pow2 prompt bucket, batch 1 (state rows scatter
        # into exactly one free slot).  ``prefill_sym`` is either a
        # length-polymorphic Symbol (one graph, ProgramCache's shape
        # keys are the buckets) or — the BucketingModule idiom, since
        # an unrolled graph bakes its length in — a callable
        # ``T -> Symbol`` invoked once per bucket.
        self._prefill_caches = {}
        self._prefill_buckets = ()
        self._prefill_data_name = prefill_data_name
        self._prefill_len_name = prefill_len_name
        if prefill_sym is not None:
            buckets, b = [], 1
            top = _next_pow2(self.max_len)
            while b <= top:
                buckets.append(b)
                b <<= 1
            self._prefill_buckets = tuple(buckets)
            from ..symbol import Symbol as _Symbol
            # Symbol is itself callable (compose), so "callable" alone
            # cannot distinguish the T -> Symbol builder idiom
            if not isinstance(prefill_sym, _Symbol) \
                    and callable(prefill_sym):
                for b in self._prefill_buckets:
                    self._prefill_caches[b] = self._build_prefill(
                        prefill_sym(b), arg_params, aux_params, ctx,
                        dtype)
            else:
                shared = self._build_prefill(prefill_sym, arg_params,
                                             aux_params, ctx, dtype)
                for b in self._prefill_buckets:
                    self._prefill_caches[b] = shared
        self._tm = (_DecodeTelemetry(self)
                    if _telemetry.enabled() else None)
        self._trace_chain = (_telemetry.chain_from_config()
                             if self._tm is not None else None)
        self._owns_http_server = (_telemetry.server.engine_acquire()
                                  if self._tm is not None else False)
        self._adm = AdmissionController(max_queue=max_queue,
                                        overload_policy=overload_policy,
                                        wake_hint=self.num_slots,
                                        telemetry=self._tm)
        n = self.num_slots
        self._slots = [None] * n        # DecodeRequest or None
        self._tokens_np = np.zeros((n,), np.float32)
        self._pos_np = np.zeros((n,), np.float32)
        self._valid_np = np.zeros((n,), np.float32)
        self._reset_np = np.zeros((n,), np.float32)
        self._states = self._program.init_states()
        self._lock = threading.Lock()
        self._step_ms = collections.deque(maxlen=4096)
        self._lat_ms = collections.deque(maxlen=4096)
        self._steps = 0
        self._joins = 0
        self._leaves = 0
        self._evictions = 0
        self._tokens_out = 0
        self._requests_served = 0
        self._abort = False
        # history/alerting plane (engine.py has the full story): the
        # scheduler loop stamps a heartbeat, the engine registers for
        # flight-recorder stats() capture, default SLO rules cover the
        # decode plane (shared burn rates + per-engine zero-progress
        # watchdog), and the recorder sampler is refcounted.
        # Registered LAST — after the failure-prone slot-pool state
        # allocation — so a constructor that raises never holds a
        # rule, heartbeat, or recorder reference close() cannot drop.
        self._hb_t = time.monotonic()
        self._hb_busy = False
        self._owns_recorder = False
        self._alert_owner = None
        self._obs_name = None
        if self._tm is not None:
            self._obs_name = "decode.%s" % self._tm.engine_label
            _telemetry.recorder.register_heartbeat(self._obs_name,
                                                   self._heartbeat)
            _telemetry.recorder.register_engine(self._obs_name, self)
            self._owns_recorder = _telemetry.recorder.recorder_acquire()
            if config.get("MXNET_TELEMETRY_ALERTS"):
                self._alert_owner = \
                    _telemetry.register_engine_default_rules(
                        "decode", self._tm.engine_label)
        self._worker = None
        if start:
            self.start()

    def _build_prefill(self, psym, arg_params, aux_params, ctx, dtype):
        """Wrap one prefill graph with the greedy head and compile-once
        plumbing: outputs become [first sampled token id] + state rows."""
        from .. import symbol as sym
        if len(psym) != 1 + len(self._program.state_names):
            raise MXNetError(
                "prefill graph has %d outputs; expected 1 (logits at "
                "the last valid position) + %d state rows"
                % (len(psym), len(self._program.state_names)))
        wrapped = sym.Group(
            [sym.argmax(psym[0], axis=1,
                        name="__decode_prefill_sample__")]
            + [psym[i] for i in range(1, len(psym))])
        return ProgramCache(
            wrapped, arg_params, aux_params,
            data_names=[self._prefill_data_name, self._prefill_len_name],
            ctx=ctx, dtype=dtype)

    # ---------------------------------------------------------- preflight
    def _preflight(self, step_sym, state_info, token_name, pos_name,
                   valid_name, strict):
        """Construction-time soundness lint: the masked step must be
        row-local along the SLOT axis with state seeded pad-dirty
        (analysis.check_decode_step) — a cross-position step would let
        one request's (or a dead slot's stale) values bleed into a
        co-resident request's tokens."""
        from ..analysis import check_decode_step, AnalysisError
        n = self.num_slots
        arg_names = set(step_sym.list_arguments())
        shapes = {token_name: (n,)}
        state_names = []
        for info in state_info:
            shapes[info["name"]] = (n,) + tuple(info["shape"])
            state_names.append(info["name"])
        for extra in (pos_name, valid_name):
            if extra in arg_names:
                shapes[extra] = (n,)
        verdict, report = check_decode_step(
            step_sym, shapes, state_names=state_names,
            valid_name=valid_name if valid_name in arg_names else None)
        self.analysis_report = report
        self.step_verdict = verdict
        if report.errors:
            if strict:
                report.raise_if_errors()
            warnings.warn("DecodeEngine: step-graph verification "
                          "failed:\n%s" % report.format())
            return
        if verdict == "cross-position":
            detail = "\n".join("  " + str(d) for d in report.warnings) \
                or "  (see report)"
            msg = ("[padding] DecodeEngine: step graph is cross-"
                   "position along the SLOT axis — co-resident "
                   "requests (and stale state in freed slots) would "
                   "contaminate each other's tokens:\n%s" % detail)
            if strict:
                raise AnalysisError(msg)
            warnings.warn(msg + "\ncontinuing because "
                          "MXNET_ANALYSIS_STRICT=0; decoded output "
                          "WILL differ from single-request decode")

    # ---------------------------------------------------------- lifecycle
    def start(self):
        if self._adm.closed:
            raise EngineClosedError(
                "engine is closed; build a new DecodeEngine")
        if self._worker is None:
            self._worker = threading.Thread(target=self._run,
                                            name="mxnet-decode-worker",
                                            daemon=True)
            self._worker.start()
        return self

    def close(self, drain=True):
        """Stop admitting.  With ``drain``, queued AND slot-resident
        requests run to completion first; otherwise queued futures
        fail with EngineClosedError and in-flight requests resolve
        with their PARTIAL tokens (finish_reason "closed")."""
        if not drain:
            self._abort = True
        self._adm.close(drain=drain)
        if self._worker is not None:
            self._worker.join(timeout=None if drain else 60)
            if not self._worker.is_alive():
                self._worker = None
        elif drain:
            self._run()     # never started: drain on the caller's thread
        if self._tm is not None:
            self._tm.close()
        if self._obs_name is not None:
            _telemetry.recorder.unregister_heartbeat(self._obs_name)
            _telemetry.recorder.unregister_engine(self._obs_name)
            self._obs_name = None
        if self._alert_owner is not None:
            _telemetry.default_manager().remove_owner(self._alert_owner)
            self._alert_owner = None
        if self._owns_recorder:
            token, self._owns_recorder = self._owns_recorder, False
            _telemetry.recorder.recorder_release(token)
        if self._owns_http_server:
            self._owns_http_server = False
            _telemetry.server.engine_release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------- client
    def submit(self, prompt, max_new_tokens=None, deadline_ms=None):
        """Enqueue one generation request; returns a Future resolving
        to a :class:`DecodeResult`.

        ``prompt`` is a non-empty sequence of token ids; generation
        continues until ``eos_id`` is sampled, ``max_new_tokens`` are
        out, the slot's ``max_len`` positions fill, or the deadline
        passes (partial result, ``expired=True``)."""
        if self._adm.closed:
            raise EngineClosedError("decode engine is closed")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("decode needs a non-empty prompt (feed at "
                             "least a BOS token)")
        if len(prompt) >= self.max_len:
            raise MXNetError(
                "prompt length %d leaves no room to generate within "
                "max_len=%d positions" % (len(prompt), self.max_len))
        cap = self.max_len - len(prompt)
        if max_new_tokens is None:
            max_new_tokens = cap
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        max_new_tokens = min(max_new_tokens, cap)
        if deadline_ms is None and self._default_deadline_s > 0:
            deadline_ms = self._default_deadline_s * 1e3
        deadline = None if not deadline_ms else \
            time.monotonic() + float(deadline_ms) / 1e3
        fut = Future()
        trace = None
        if self._tm is not None:
            self._tm.requests.inc()
            if self._trace_chain is not None:
                trace = _telemetry.LazyTrace(self._trace_chain,
                                             name="decode.request")
        req = DecodeRequest(prompt, max_new_tokens, fut,
                            deadline=deadline, trace=trace)
        # a deadline hit — queued or mid-generation — COMPLETES the
        # request with whatever was generated (admission._deliver
        # routes DeadlineExceededError through this instead of failing)
        req.on_expire = lambda exc, r=req: DecodeResult(
            r.tokens, "deadline", n_steps=r.n_steps,
            prompt_len=len(r.prompt))
        try:
            self._adm.admit(req)
        except Exception as e:
            if trace is not None:
                trace.abort(type(e).__name__)
            raise
        return fut

    def generate(self, prompt, max_new_tokens=None, deadline_ms=None,
                 timeout=None):
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout=timeout)

    # ------------------------------------------------------------- worker
    def _occupied(self):
        return [i for i, s in enumerate(self._slots) if s is not None]

    def _occupied_count(self):
        return sum(1 for s in self._slots if s is not None)

    def _heartbeat(self):
        """Watchdog probe: progress age of the scheduler loop, busy
        when any slot is generating or work is queued.  A step program
        wedged in dispatch (donated-buffer failure modes, a hung
        backend) shows up as busy + growing age — named by this
        heartbeat, not inferred from throughput silence."""
        now = time.monotonic()
        queued = len(self._adm)
        occupied = self._occupied_count()
        return {"age_s": now - self._hb_t,
                "busy": bool(self._hb_busy or queued or occupied),
                "in_step": bool(self._hb_busy),
                "queued": queued, "slots_occupied": occupied,
                "kind": "decode",
                "engine": (self._tm.engine_label
                           if self._tm is not None else None)}

    def _run(self):
        while True:
            self._hb_t = time.monotonic()
            self._hb_busy = False
            try:
                if self._abort:
                    for i in self._occupied():
                        self._finish_slot(i, "closed")
                    return
                occ = self._occupied()
                free = self.num_slots - len(occ)
                if not occ:
                    batch = self._adm.take(free, 0.0)
                    if batch is None:
                        return          # closed and drained
                    for r in batch:
                        self._join(r)
                    continue
                # busy: admit opportunistically (never block a step),
                # and keep queued deadlines honest even when no slot
                # is free — expiry must not wait for a drain
                if free:
                    for r in self._adm.poll(free):
                        self._join(r)
                else:
                    self._adm.sweep()
                self._hb_busy = True    # a wedged step must read busy
                self._step_once()
            except Exception as e:      # fail the batch, keep serving
                for i in self._occupied():
                    req = self._slots[i]
                    self._slots[i] = None
                    self._valid_np[i] = 0.0
                    if not req.future.done():
                        _fail_future(req.future, e)
                    if req.trace is not None:
                        req.trace.abort(type(e).__name__)
                # a failed step dispatch may have consumed the DONATED
                # state buffers (non-CPU backends): self._states would
                # point at deleted arrays and wedge every later step —
                # the pool is empty now, so fresh zeros lose nothing
                self._states = self._program.init_states()
                self._tokens_np.fill(0.0)
                self._pos_np.fill(0.0)
                self._reset_np.fill(0.0)

    def _join(self, req):
        """Seat one admitted request in a free slot BETWEEN steps: zero
        (or prefill-fill) the slot's state rows, stage its first token,
        mark the slot valid.  No shape changes anywhere — the next step
        dispatch reuses the same compiled program."""
        if not req.future.set_running_or_notify_cancel():
            if req.trace is not None:
                req.trace.abort("cancelled")
            with self._lock:
                self._leaves += 1     # stats() and the leaves series
            if self._tm is not None:  # must carry the same numbers
                self._tm.leave("cancelled")
            return
        slot = self._slots.index(None)
        req.slot = slot
        req.t_join = time.perf_counter()
        self._slots[slot] = req
        self._valid_np[slot] = 1.0
        with self._lock:
            self._joins += 1
        if self._tm is not None:
            self._tm.joins.inc()
        if self._prefill_caches:
            # a broken prefill dispatch is THIS request's failure, not
            # the batch's: co-resident mid-generation requests share no
            # state with it and must keep their partial generations
            try:
                self._prefill(req, slot)
            except Exception as e:
                self._slots[slot] = None
                self._valid_np[slot] = 0.0
                with self._lock:
                    self._leaves += 1
                if self._tm is not None:
                    self._tm.leave("error")
                _fail_future(req.future, e)
                if req.trace is not None:
                    req.trace.abort(type(e).__name__)
                return
        else:
            # the previous occupant's state rows are cleared IN the
            # next step dispatch (StepProgram reset mask) — a join
            # costs zero device traffic of its own
            self._reset_np[slot] = 1.0
            self._tokens_np[slot] = req.prompt[0]
            self._pos_np[slot] = 0.0
            req.prompt_i = 1
        self._check_finish(slot)

    def _prefill(self, req, slot):
        """One bucketed dispatch consumes the whole prompt: pad onto
        the pow2 bucket grid, run the prefill program (batch 1), argmax
        the last-valid-position logits into the first generated token,
        scatter the output state rows into the free slot."""
        plen = len(req.prompt)
        bucket = next(b for b in self._prefill_buckets if b >= plen)
        arr = np.zeros((1, bucket), np.float32)
        arr[0, :plen] = req.prompt
        feeds = {self._prefill_data_name: arr,
                 self._prefill_len_name: np.asarray([plen], np.float32)}
        outs = self._prefill_caches[bucket].run(feeds)
        first = outs[0][0]
        rows = {name: outs[1 + i][0]
                for i, name in enumerate(self._program.state_names)}
        self._states = self._program.write_row(self._states, slot, rows)
        self._reset_np[slot] = 0.0      # prefill rows are live data
        req.prompt_i = plen
        req.tokens.append(int(first))
        now = time.monotonic()
        req.t_first_tok = req.t_last_tok = now
        self._tokens_np[slot] = first
        self._pos_np[slot] = float(plen)
        with self._lock:
            self._tokens_out += 1
        if self._tm is not None:
            self._tm.tokens.inc()
            self._tm.ttft.observe(now - req.t_enqueue)

    def _step_once(self):
        t0 = time.perf_counter()
        now = time.monotonic()
        # per-iteration deadline check: an expired slot-resident
        # request completes with its partial tokens and frees the slot
        # for queued work — mid-generation eviction, not failure
        for i in self._occupied():
            if self._slots[i].expired(now):
                self._finish_slot(i, "deadline")
        occ = self._occupied()
        if not occ:
            return
        sampled, self._states = self._program.step(
            self._tokens_np, self._pos_np, self._valid_np, self._states,
            reset=self._reset_np)
        self._reset_np.fill(0.0)        # consumed: rows are zeroed now
        new_tokens = 0
        t_tok = time.monotonic()        # one stamp serves every slot
        for i in occ:
            req = self._slots[i]
            req.n_steps += 1
            self._pos_np[i] += 1.0
            if req.prompt_i < len(req.prompt):
                # teacher forcing: the sample is discarded, the next
                # prompt token rides the next step
                self._tokens_np[i] = req.prompt[req.prompt_i]
                req.prompt_i += 1
            else:
                req.tokens.append(int(sampled[i]))
                self._tokens_np[i] = sampled[i]
                new_tokens += 1
                if req.t_first_tok is None:
                    req.t_first_tok = t_tok
                    if self._tm is not None:
                        self._tm.ttft.observe(t_tok - req.t_enqueue)
                req.t_last_tok = t_tok
            self._check_finish(i)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._steps += 1
            self._tokens_out += new_tokens
            self._step_ms.append(dt_ms)
        if self._tm is not None:
            self._tm.steps.inc()
            if new_tokens:
                self._tm.tokens.inc(new_tokens)
            self._tm.step_ms.observe(dt_ms)

    def _check_finish(self, slot):
        req = self._slots[slot]
        if req is None or not req.tokens:
            return
        if self.eos_id is not None and req.tokens[-1] == self.eos_id:
            self._finish_slot(slot, "eos")
        elif len(req.tokens) >= req.max_new:
            self._finish_slot(slot, "length")
        elif self._pos_np[slot] >= self.max_len:
            # no position left to consume the staged token at: the
            # fixed O(1) cache layout is full
            self._finish_slot(slot, "length")

    def _finish_slot(self, slot, reason):
        """Leave the batch between steps: deliver the result, mark the
        slot dead (valid=0) — its state rows stay as stale garbage,
        which the row-local step verdict proves harmless, and the next
        join rewrites them."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._valid_np[slot] = 0.0
        self._tokens_np[slot] = 0.0
        self._pos_np[slot] = 0.0
        now = time.monotonic()
        t1 = time.perf_counter()
        res = DecodeResult(req.tokens, reason, n_steps=req.n_steps,
                           prompt_len=len(req.prompt))
        if not req.future.cancelled():
            try:
                req.future.set_result(res)
            except Exception:
                pass
        with self._lock:
            self._leaves += 1
            self._requests_served += 1
            if reason == "deadline":
                self._evictions += 1
            self._lat_ms.append((now - req.t_enqueue) * 1e3)
        if self._tm is not None:
            self._tm.leave(reason)
            if reason == "deadline":
                self._tm.evictions.inc()
            if len(req.tokens) >= 2 and req.t_first_tok is not None \
                    and req.t_last_tok is not None:
                # mean inter-token gap over this request's generation:
                # one observation per request keeps the hot loop at
                # O(1) instrument calls while the histogram still
                # carries the per-request tail the counter cannot
                self._tm.tpot.observe(
                    (req.t_last_tok - req.t_first_tok)
                    / (len(req.tokens) - 1))
        if req.trace is not None:
            t_join = req.t_join if req.t_join is not None else t1

            def build(tc, _req=req, _t_join=t_join, _t1=t1,
                      _reason=reason):
                tc.add("queue-wait", tc.root.t0, _t_join, "serve")
                tc.add("decode", _t_join, _t1, "serve",
                       meta={"steps": _req.n_steps,
                             "tokens": len(_req.tokens),
                             "prompt_len": len(_req.prompt),
                             "finish_reason": _reason})
            req.trace.finish(t1, build=build)

    # ------------------------------------------------------------ observe
    def warmup(self):
        """Compile everything live traffic will ever dispatch: the
        persistent step program, the per-state row-write kernels, and
        (with a prefill graph) one program per pow2 prompt bucket.
        After this, joins/leaves/steps never trace — tests pin
        ``compile_count`` across churn.  Returns the compile count.

        The step runs TWICE on purpose: jax's executable cache keys on
        argument sharding, and the kernel's own state outputs (every
        live iteration's inputs) carry committed shardings that fresh
        ``init_states`` buffers don't — one warm step would leave the
        first live iteration paying a silent ~100ms recompile that the
        trace counter cannot even see.  The row-write kernel likewise
        warms against both a fresh buffer and a stepped one (the two
        shardings a prefill scatter can meet)."""
        states = self._program.init_states()
        states = self._program.zero_row(states, 0)
        n = self.num_slots
        z = np.zeros((n,), np.float32)
        _, states = self._program.step(z, z, z, states)
        _, states = self._program.step(z, z, z, states)
        rows = {}
        for info in self._program.state_info:
            dt = np.dtype(info.get("dtype") or self._program._dtype)
            rows[info["name"]] = np.zeros(tuple(info["shape"]), dt)
        self._program.write_row(states, 0, rows)
        for b in self._prefill_buckets:
            feeds = {self._prefill_data_name:
                     np.zeros((1, b), np.float32),
                     self._prefill_len_name:
                     np.zeros((1,), np.float32)}
            self._prefill_caches[b].run(feeds)
        return self.compile_count

    @property
    def compile_count(self):
        c = self._program.trace_count
        seen = set()
        for cache in self._prefill_caches.values():
            if id(cache) not in seen:       # shared length-poly cache
                seen.add(id(cache))
                c += cache.compile_count
        return c

    def stats(self):
        """Admission counters plus the ``decode`` block: slot-pool
        geometry and occupancy, step/token/join/leave/eviction
        counts, per-step and end-to-end latency percentiles — the
        same numbers the ``mxnet_serve_decode_*`` series carry."""
        snap = self._adm.stats()
        with self._lock:
            step = sorted(self._step_ms)
            lat = sorted(self._lat_ms)
            snap["decode"] = {
                "slots": self.num_slots,
                "slots_occupied": self._occupied_count(),
                "max_len": self.max_len,
                "steps": self._steps,
                "tokens_generated": self._tokens_out,
                "joins": self._joins,
                "leaves": self._leaves,
                "evictions": self._evictions,
                "requests_served": self._requests_served,
                "compile_count": self.compile_count,
                "prefill": ("bucket" if self._prefill_caches
                            else "step"),
                "prefill_buckets": list(self._prefill_buckets),
                "step_ms": {
                    "count": len(step),
                    "mean": float(np.mean(step)) if step else 0.0,
                    "p50": _percentile(step, 0.50),
                    "p99": _percentile(step, 0.99),
                },
                "latency_ms": {
                    "count": len(lat),
                    "mean": float(np.mean(lat)) if lat else 0.0,
                    "p50": _percentile(lat, 0.50),
                    "p99": _percentile(lat, 0.99),
                },
            }
        return snap
