"""Request-scoped tracing: one span tree per traced request.

Metrics (metrics.py) aggregate; they cannot answer "where did THIS
request's 40 ms go?".  A :class:`TraceContext` carries a trace id plus
a span stack through a request's whole life — admission, coalescing,
padding, program dispatch, unpadding — across the thread hop from the
submitting client to the serving worker:

- on the *submitting* thread the context is contextvar-propagated, so
  nested code (executor forward, cached-op dispatch) can attach spans
  without plumbing arguments;
- across the *worker* hop it rides the queued ``Request`` object and
  the engine records batch-stage spans onto every member trace
  explicitly (contextvars do not cross threads by design).

Finished traces land in a bounded in-process store retrievable by
trace id (``MXNET_TELEMETRY_TRACE_CAPACITY``, oldest evicted) — the
source ``tools/telemetry_dump.py`` and the live ``/traces`` endpoint
render span breakdowns from — and every span is bridged into the
:mod:`mxnet_tpu.profiler` Chrome-trace ring as a categorized event
carrying its ``trace_id`` arg, so one perfetto timeline shows requests
and host regions interleaved.

A TraceContext built with a ``retention`` chain (sampling.py) defers
the keep/drop decision to ``finish()``, when the end-to-end latency is
known: dropped traces are never stored nor bridged (they cost one
discarded object), kept traces carry a ``retained_by`` tag.  Without a
chain, ``finish()`` stores unconditionally — the explicit
``telemetry.trace(...)`` entry point keeps its PR 3 contract.

Span timestamps use ``time.perf_counter()`` — the same clock the
profiler ring is anchored to.
"""
from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import random
import threading
import time

__all__ = ["Span", "TraceContext", "LazyTrace", "current_trace",
           "activate", "trace", "maybe_span", "get_trace",
           "recent_trace_ids", "all_traces", "clear_traces",
           "store_capacity"]

_CURRENT = contextvars.ContextVar("mxnet_tpu_trace", default=None)

_STORE_LOCK = threading.Lock()
_STORE = collections.OrderedDict()      # trace_id -> finished tree dict

# Trace ids: 24 random bits fixed per process + a 40-bit atomic counter
# (itertools.count is GIL-atomic), formatted to the same 16 hex chars as
# the old uuid4 prefix.  uuid4 costs a urandom syscall (~70 us on this
# class of host) — unaffordable now that EVERY serving request carries
# a TraceContext and retention is decided at finish.
_ID_BASE = random.getrandbits(24)
_ID_SEQ = itertools.count()


def _new_trace_id():
    return "%06x%010x" % (_ID_BASE, next(_ID_SEQ) & 0xFFFFFFFFFF)


def store_capacity():
    from .. import config
    return config.get("MXNET_TELEMETRY_TRACE_CAPACITY")


class Span(object):
    """One timed region.  ``t0``/``t1`` are perf_counter seconds;
    ``meta`` holds small JSON-able annotations (bucket size, compile
    flag)."""
    __slots__ = ("name", "cat", "t0", "t1", "children", "meta")

    def __init__(self, name, cat="span", t0=None):
        self.name = name
        self.cat = cat
        self.t0 = time.perf_counter() if t0 is None else t0
        self.t1 = None
        self.children = []
        self.meta = None

    @property
    def dur_ms(self):
        if self.t1 is None:
            return None
        return (self.t1 - self.t0) * 1e3

    def to_dict(self, origin):
        d = {"name": self.name, "cat": self.cat,
             "start_ms": round((self.t0 - origin) * 1e3, 4),
             "dur_ms": (None if self.t1 is None
                        else round((self.t1 - self.t0) * 1e3, 4))}
        if self.meta:
            d["meta"] = dict(self.meta)
        if self.children:
            d["children"] = [c.to_dict(origin) for c in self.children]
        return d


class TraceContext(object):
    """Trace id + span stack for one logical request.

    Mutations are lock-guarded: a trace is touched by at most one
    thread at a time, but by *different* threads over its life
    (client submit -> engine worker), and the lock makes the handoff
    safe without any happens-before choreography at the call sites.
    """
    __slots__ = ("trace_id", "root", "_stack", "_lock", "finished",
                 "retention", "failed_reason")

    def __init__(self, name="request", cat="trace", retention=None):
        self.trace_id = _new_trace_id()
        self.root = Span(name, cat)
        self._stack = [self.root]
        self._lock = threading.Lock()
        self.finished = False
        # sampling.SamplerChain (or None = always keep): consulted once
        # at finish(), when the e2e latency is known
        self.retention = retention
        self.failed_reason = None

    # -- structured recording ---------------------------------------------
    @contextlib.contextmanager
    def span(self, name, cat="span", meta=None):
        """Nested timed region on the current thread's stack."""
        sp = self.begin(name, cat, meta)
        try:
            yield sp
        finally:
            self.end(sp)

    def begin(self, name, cat="span", meta=None):
        sp = Span(name, cat)
        if meta:
            sp.meta = dict(meta)
        with self._lock:
            self._stack[-1].children.append(sp)
            self._stack.append(sp)
        return sp

    def end(self, sp, t1=None):
        t1 = time.perf_counter() if t1 is None else t1
        with self._lock:
            sp.t1 = t1
            # tolerate out-of-order ends (cross-thread handoff): pop
            # only through the span being closed
            if sp in self._stack:
                while self._stack[-1] is not sp:
                    dangling = self._stack.pop()
                    if dangling.t1 is None:
                        dangling.t1 = t1
                self._stack.pop()

    def add(self, name, t0, t1, cat="span", meta=None):
        """Record an already-measured interval as a child of the
        current open span (the cross-thread path: the engine worker
        measured the batch stage once and attributes it to every
        member request's trace)."""
        sp = Span(name, cat, t0=t0)
        sp.t1 = t1
        if meta:
            sp.meta = dict(meta)
        with self._lock:
            self._stack[-1].children.append(sp)
        return sp

    # -- lifecycle ---------------------------------------------------------
    def abort(self, reason):
        """Finish a trace whose request never completed (rejected,
        shed, expired, cancelled, dispatch error): a zero-length
        'failed' child records why, so overloaded/slow traffic — the
        traffic an operator is debugging — still leaves a record."""
        if self.finished:
            return
        self.failed_reason = str(reason)
        t = time.perf_counter()
        self.add("failed", t, t, "serve", meta={"reason": str(reason)})
        self.finish(t)

    def finish(self, t1=None, retained_by=None):
        """Close the root; when the retention chain (if any) votes
        keep, publish the tree to the bounded store and bridge every
        span into the profiler ring (when running) — a dropped trace
        inserts nothing and bridges nothing.  ``retained_by`` tags the
        stored tree when the keep decision was made EXTERNALLY
        (:class:`LazyTrace` decides before this object even exists).
        """
        with self._lock:
            if self.finished:
                return
            self.finished = True
            t1 = time.perf_counter() if t1 is None else t1
            for sp in self._stack[::-1]:
                if sp.t1 is None:
                    sp.t1 = t1
            self._stack = [self.root]
        if self.retention is not None:
            keep, retained_by = self.retention.decide(
                (t1 - self.root.t0) * 1e3, self.failed_reason)
            if not keep:
                return
        tree = self.to_dict()
        if retained_by is not None:
            tree["retained_by"] = retained_by
        with _STORE_LOCK:
            _STORE[self.trace_id] = tree
            cap = store_capacity()
            while len(_STORE) > cap:
                _STORE.popitem(last=False)
        self._bridge_to_profiler()
        self._feed_timeline()
        # push the keep to live /events subscribers (SSE): only the
        # retained minority reaches this line, so the dropped-path
        # cost stays zero; a hub failure must never fail a request
        try:
            from .server import publish_event
            root = tree.get("root", {})
            publish_event("trace", {
                "trace_id": self.trace_id, "name": root.get("name"),
                "dur_ms": root.get("dur_ms"),
                "retained_by": tree.get("retained_by"),
                "failed": self.failed_reason})
        except Exception:
            pass

    def to_dict(self):
        from . import timeline
        root = self.root.to_dict(self.root.t0)
        # wall anchor of the root: every span's start_ms offsets from
        # here, which is how request_autopsy joins the tree against
        # wall-stamped timeline events
        root["t0_wall"] = timeline.wall_of_perf(self.root.t0)
        return {"trace_id": self.trace_id, "root": root}

    def _bridge_to_profiler(self):
        from .. import profiler
        if not profiler.is_running():
            return
        args = {"trace_id": self.trace_id}

        def walk(sp):
            profiler.add_span_event(sp.name, sp.cat, sp.t0,
                                    sp.t1 if sp.t1 is not None else sp.t0,
                                    args=args)
            for c in sp.children:
                walk(c)
        walk(self.root)

    def _feed_timeline(self):
        """Mirror the retained tree into the fleet timeline — only the
        kept minority pays, and a dropped trace appends nothing."""
        from . import timeline
        if not timeline.enabled():
            return
        tl = timeline.get()
        args = {"trace": self.trace_id}

        def walk(sp):
            tl.complete(sp.name, sp.cat, "trace", sp.t0,
                        sp.t1 if sp.t1 is not None else sp.t0,
                        args=args)
            for c in sp.children:
                walk(c)
        walk(self.root)


class LazyTrace(object):
    """The cost-free way to trace EVERY serving request: one timestamp
    at submit, one retention decision at finish — a real
    :class:`TraceContext` (spans, store insert, profiler bridge) is
    built ONLY for the kept minority, retroactively, from timestamps
    the engine already holds.

    The serving hot path pays ~one object allocation plus the sampler
    chain's decision per dropped request; everything else (trace id,
    span objects, locks, tree rendering) is deferred behind the keep
    verdict.  Quacks like TraceContext where the engine and admission
    controller touch it: ``abort(reason)`` on every failure path, and
    ``finish(t1, build)`` where ``build(tc)`` attaches the batch-stage
    spans to the freshly materialized context.
    """
    __slots__ = ("t0", "retention", "finished", "name", "cat")

    def __init__(self, retention, name="serve.request", cat="serve"):
        self.t0 = time.perf_counter()
        self.retention = retention
        self.finished = False
        self.name = name
        self.cat = cat

    def _materialize(self):
        tc = TraceContext(self.name, self.cat)
        tc.root.t0 = self.t0
        return tc

    def finish(self, t1=None, build=None):
        """Decide retention; when kept, materialize the TraceContext,
        let ``build(tc)`` attach spans, and publish."""
        if self.finished:
            return
        self.finished = True
        t1 = time.perf_counter() if t1 is None else t1
        keep, why = self.retention.decide((t1 - self.t0) * 1e3, None)
        if not keep:
            return
        tc = self._materialize()
        if build is not None:
            build(tc)
        tc.finish(t1, retained_by=why)

    def abort(self, reason):
        """Failure path (rejected/shed/expired/cancelled/dispatch
        error): decide with the failure reason — the error sampler
        keeps these unconditionally — and record why."""
        if self.finished:
            return
        self.finished = True
        t1 = time.perf_counter()
        keep, why = self.retention.decide((t1 - self.t0) * 1e3,
                                          str(reason))
        if not keep:
            return
        tc = self._materialize()
        tc.failed_reason = str(reason)
        tc.add("failed", t1, t1, "serve", meta={"reason": str(reason)})
        tc.finish(t1, retained_by=why)


# -- contextvar propagation (same-thread nesting) ---------------------------

def current_trace():
    """The TraceContext active on this thread's context, or None."""
    return _CURRENT.get()


@contextlib.contextmanager
def activate(tc):
    """Make ``tc`` the current trace for the enclosed block (does not
    finish it — ownership stays with the caller)."""
    token = _CURRENT.set(tc)
    try:
        yield tc
    finally:
        _CURRENT.reset(token)


@contextlib.contextmanager
def trace(name="request", cat="trace"):
    """Create, activate, and on exit finish a TraceContext — the
    entry point for tracing an eager/training region by hand::

        with telemetry.trace("step") as tc:
            ...
        tree = telemetry.get_trace(tc.trace_id)
    """
    tc = TraceContext(name, cat)
    with activate(tc):
        try:
            yield tc
        finally:
            tc.finish()


@contextlib.contextmanager
def maybe_span(name, cat="span", meta=None):
    """Span on the current trace when one is active; no-op otherwise.
    The cheap hook library code (executor, cached_op) uses."""
    tc = _CURRENT.get()
    if tc is None or tc.finished:
        yield None
        return
    with tc.span(name, cat, meta) as sp:
        yield sp


# -- finished-trace store ---------------------------------------------------

def get_trace(trace_id):
    """Span tree dict for a finished trace, or None if unknown/evicted."""
    with _STORE_LOCK:
        return _STORE.get(trace_id)


def recent_trace_ids():
    """Trace ids currently in the store, oldest first."""
    with _STORE_LOCK:
        return list(_STORE)


def all_traces():
    """{trace_id: tree} snapshot of the store (for dump files)."""
    with _STORE_LOCK:
        return dict(_STORE)


def clear_traces():
    with _STORE_LOCK:
        _STORE.clear()
