"""Training-step attribution report: where does each step's wall go?

Renders the ``mxnet_train_*`` series (telemetry/step.py) as one
attribution table per training loop — phase rows (data_wait / h2d /
fwd_bwd / kv_push / kv_pull / optimizer / metric), an explicit
**unattributed residual** row (step wall minus the phase sum — the
breakdown must confess what it could not attribute), the phase
coverage ratio, and the MFU / FLOPs / compile / device-memory scalars
— plus the input-pipeline production histograms (io.py) next to the
loop's measured data_wait so "iterator too slow" vs "loop never
waited" is one read.

Sources: a telemetry JSON snapshot (``telemetry.dump_state``, the
snapshot thread, or a rank snapshot), a live endpoint via ``--url``,
or SEVERAL rank snapshots — which are aggregated first
(tools/telemetry_dump.py machinery), rendering the fleet-summed
``rank="all"`` table and a per-phase straggler section naming the rank
whose mean phase time is largest::

  python tools/step_report.py telemetry.json
  python tools/step_report.py --url http://host:9100
  python tools/step_report.py shared/telemetry_rank*.json   # straggler view
"""
import argparse
import json
import sys

from telemetry_dump import load_doc, aggregate_docs, _doc_rank

#: canonical row order (telemetry/step.py PHASES); unknown phases sort after
PHASE_ORDER = ("data_wait", "h2d", "fwd_bwd", "kv_push", "kv_pull",
               "optimizer", "metric")

RESIDUAL_ROW = "unattributed residual"


def _series(metrics, name):
    return (metrics.get(name) or {}).get("series", [])


def _scalar(metrics, name, loop, rank, reduce=None):
    """One scalar for (loop, rank).  Aggregated documents carry no
    rank="all" series for GAUGES (aggregate_docs only spreads them),
    so when asked for the fleet value this falls back to reducing the
    per-rank series with ``reduce`` (mean for ratios like MFU, max for
    watermarks)."""
    vals = []
    for s in _series(metrics, name):
        lab = s.get("labels", {})
        if lab.get("loop") != loop:
            continue
        srank = lab.get("rank", rank)
        if srank == rank:
            return s.get("value")
        if rank == "all" and s.get("value") is not None:
            vals.append(s["value"])
    if rank == "all" and vals and reduce is not None:
        return reduce(vals)
    return None


def build_report(doc):
    """{(loop, rank): table dict} from one (possibly aggregated)
    telemetry document.  ``rank`` is None for single-host snapshots;
    aggregated docs contribute their ``rank="all"`` fleet sums."""
    metrics = doc.get("metrics", {})
    out = {}
    for s in _series(metrics, "mxnet_train_step_seconds"):
        lab = s.get("labels", {})
        if not s.get("count"):
            continue
        key = (lab.get("loop", "?"), lab.get("rank"))
        if key[1] is not None and key[1] != "all":
            continue        # per-rank detail lives in the straggler view
        out[key] = {"loop": key[0], "rank": key[1],
                    "steps": s["count"], "wall_s": s["sum"] or 0.0,
                    "phases": {}}
    for s in _series(metrics, "mxnet_train_step_phase_seconds"):
        lab = s.get("labels", {})
        key = (lab.get("loop", "?"), lab.get("rank"))
        row = out.get(key)
        if row is None or not s.get("count"):
            continue
        row["phases"][lab.get("phase", "?")] = {
            "steps": s["count"], "total_s": s["sum"] or 0.0}
    for key, row in out.items():
        loop, rank = key
        attributed = sum(p["total_s"] for p in row["phases"].values())
        row["attributed_s"] = attributed
        row["residual_s"] = max(row["wall_s"] - attributed, 0.0)
        row["coverage"] = attributed / row["wall_s"] if row["wall_s"] \
            else 0.0
        mean = lambda vs: sum(vs) / len(vs)     # noqa: E731
        for name, field, reduce in (
                ("mxnet_train_mfu", "mfu", mean),
                ("mxnet_train_step_flops", "step_flops", max),
                ("mxnet_train_steps_total", "steps_total", sum),
                ("mxnet_train_step_compiles_total", "compile_steps", sum),
                ("mxnet_train_device_mem_peak_bytes",
                 "device_mem_peak_bytes", max)):
            v = _scalar(metrics, name, loop, rank, reduce)
            if v is not None:
                row[field] = v
    return out


def _phase_sort_key(name):
    try:
        return (0, PHASE_ORDER.index(name))
    except ValueError:
        return (1, name)


def format_table(row):
    lines = []
    wall, steps = row["wall_s"], row["steps"]
    head = "loop=%s" % row["loop"]
    if row.get("rank"):
        head += " rank=%s" % row["rank"]
    lines.append("%s  (%d steps, wall %.3f s, %.2f ms/step)"
                 % (head, steps, wall, wall / steps * 1e3 if steps else 0))
    lines.append("  %-24s %6s %10s %10s %8s"
                 % ("phase", "steps", "total s", "ms/step", "% wall"))
    for name in sorted(row["phases"], key=_phase_sort_key):
        p = row["phases"][name]
        lines.append("  %-24s %6d %10.4f %10.3f %7.2f%%"
                     % (name, p["steps"], p["total_s"],
                        p["total_s"] / p["steps"] * 1e3 if p["steps"] else 0,
                        p["total_s"] / wall * 1e2 if wall else 0))
    lines.append("  %-24s %6s %10.4f %10.3f %7.2f%%"
                 % (RESIDUAL_ROW, "-", row["residual_s"],
                    row["residual_s"] / steps * 1e3 if steps else 0,
                    row["residual_s"] / wall * 1e2 if wall else 0))
    lines.append("  phase coverage: %.2f%% of step wall"
                 % (row["coverage"] * 1e2))
    scal = []
    if row.get("mfu"):
        scal.append("mfu=%.4f" % row["mfu"])
    if row.get("step_flops"):
        scal.append("step_flops=%.4g" % row["step_flops"])
    if row.get("compile_steps") is not None:
        scal.append("steps_with_compiles=%d" % row["compile_steps"])
    if row.get("device_mem_peak_bytes"):
        scal.append("device_mem_peak=%.4g MB"
                    % (row["device_mem_peak_bytes"] / 1e6))
    if scal:
        lines.append("  " + "  ".join(scal))
    return "\n".join(lines)


def format_io(metrics):
    """Input-pipeline production cost next to the loop's data_wait."""
    rows = []
    for s in _series(metrics, "mxnet_io_batch_latency_ms"):
        if not s.get("count"):
            continue
        lab = s.get("labels", {})
        if lab.get("rank") not in (None, "all"):
            continue
        rows.append("  %-24s batches=%-6d mean=%.3f ms"
                    % (lab.get("iter", "?"), s["count"],
                       (s["sum"] or 0.0) / s["count"]))
    if not rows:
        return ""
    return ("input pipeline (production cost; the loop's data_wait is "
            "the blocked share):\n" + "\n".join(rows))


def format_stragglers(doc):
    """Per-phase straggler attribution from the aggregate's
    histogram-mean spread: the max_rank is the straggling rank."""
    spread = (doc.get("histogram_spread") or {}).get(
        "mxnet_train_step_phase_seconds") or {}
    rows = []
    for labels, v in sorted(spread.items(),
                            key=lambda kv: -kv[1]["spread"]):
        rows.append("  %-40s straggler rank %s (mean %.3f ms; fastest "
                    "rank %s at %.3f ms, spread %.3f ms)"
                    % (labels, v["max_rank"], v["max"] * 1e3,
                       v["min_rank"], v["min"] * 1e3, v["spread"] * 1e3))
    if not rows:
        return ""
    return "per-phase straggler attribution (widest spread first):\n" \
        + "\n".join(rows)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render the training-step attribution table")
    ap.add_argument("files", nargs="*",
                    help="telemetry JSON snapshot(s); two or more "
                         "rank snapshots are aggregated first")
    ap.add_argument("--url",
                    help="scrape a live MXNET_TELEMETRY_PORT endpoint "
                         "instead of reading files")
    ap.add_argument("--loop", help="only report this loop label")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report instead of text")
    args = ap.parse_args(argv)

    if args.url:
        doc = load_doc(args.url)
    elif len(args.files) == 1:
        doc = load_doc(args.files[0])
    elif len(args.files) > 1:
        used, entries = set(), []
        for i, src in enumerate(args.files):
            d = load_doc(src)
            if "text" in d:
                print("step_report needs JSON snapshots; %r is "
                      "Prometheus text" % src, file=sys.stderr)
                return 2
            entries.append((_doc_rank(d, src, i, used), d))
        doc = aggregate_docs(entries)
    else:
        print("step_report: pass snapshot file(s) or --url "
              "http://host:port", file=sys.stderr)
        return 2
    if "text" in doc:
        print("step_report needs a JSON snapshot (got Prometheus "
              "text); re-dump with MXNET_TELEMETRY_SNAPSHOT_FORMAT="
              "json or use /metrics.json", file=sys.stderr)
        return 2

    report = build_report(doc)
    if args.loop:
        report = {k: v for k, v in report.items() if k[0] == args.loop}
    if args.as_json:
        out = {"loops": sorted(report.values(),
                               key=lambda r: (r["loop"], r["rank"] or "")),
               "histogram_spread": doc.get("histogram_spread") or {}}
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    if not report:
        print("(no mxnet_train_step_seconds series — did the loop run "
              "with MXNET_TELEMETRY_ON=1?)")
        return 1
    blocks = [format_table(report[k]) for k in sorted(
        report, key=lambda k: (k[0], k[1] or ""))]
    io_block = format_io(doc.get("metrics", {}))
    if io_block:
        blocks.append(io_block)
    straggler = format_stragglers(doc)
    if straggler:
        blocks.append(straggler)
    print("\n\n".join(blocks))
    return 0


if __name__ == "__main__":
    sys.exit(main())
