#!/usr/bin/env python
"""MNIST training (example/image-classification/train_mnist.py).

Uses the MNISTIter over idx-format files when --data-dir holds them, else
a synthetic stand-in so the example runs anywhere (zero egress).
"""
import argparse
import os

import numpy as np

from common import add_fit_args, fit


def get_iters(args):
    import mxnet_tpu as mx
    files = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]
    if args.data_dir and all(os.path.exists(os.path.join(args.data_dir, f))
                             for f in files):
        train = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, files[0]),
            label=os.path.join(args.data_dir, files[1]),
            batch_size=args.batch_size, shuffle=True, flat=False)
        val = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, files[2]),
            label=os.path.join(args.data_dir, files[3]),
            batch_size=args.batch_size, flat=False)
        return train, val
    print("no MNIST files under %r — using a synthetic stand-in"
          % args.data_dir)
    rng = np.random.default_rng(0)
    protos = [np.kron(rng.random((7, 7)).astype(np.float32),
                      np.ones((4, 4), np.float32)) for _ in range(10)]
    X, Y = [], []
    for k, pr in enumerate(protos):
        for _ in range(200):
            X.append(np.clip(pr + rng.normal(0, 0.25, (28, 28)), 0, 1))
            Y.append(k)
    X = np.stack(X)[:, None].astype(np.float32) - 0.5
    Y = np.asarray(Y, np.float32)
    order = rng.permutation(len(Y))
    X, Y = X[order], Y[order]
    n = int(len(Y) * 0.9)
    train = mx.io.NDArrayIter(X[:n], Y[:n], args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(X[n:], Y[n:], args.batch_size,
                            label_name="softmax_label")
    return train, val


def main():
    p = argparse.ArgumentParser(description=__doc__)
    add_fit_args(p)
    p.add_argument("--data-dir", default="data/mnist")
    p.set_defaults(network="lenet", num_epochs=5, lr=0.05, batch_size=64)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu.models import get_lenet, get_mlp
    net = get_mlp() if args.network == "mlp" else get_lenet()
    train, val = get_iters(args)
    mod = mx.mod.Module(net, context=mx.gpu())
    fit(args, mod, train, val)
    acc = mx.metric.Accuracy()
    val.reset()
    mod.score(val, acc)
    print("final validation %s: %.4f" % acc.get())


if __name__ == "__main__":
    main()
