"""SLO-driven overload regulator — the actuator half of ROADMAP item 3
(ISSUE 12).

The sensor plane is done (PR 9): ``recorder.rate()`` windows, the
``serve_queue_saturation_burn`` and ``serve_deadline_miss_burn``
burn-rate rules.  Until now a firing rule PAGED — an operator read the
flight bundle and tightened admission by hand.  This module closes the
loop in-process: a per-engine regulator thread reads the burn-rule
states (and the request-rate window, for the record) every evaluation
cycle and adapts the engine's :class:`AdmissionController`:

- **tighten** while a watched rule FIRES: the effective queue limit
  halves per cycle (never below ``MXNET_REGULATOR_MIN_QUEUE``), and
  the controller sheds down to it **cost-aware** — the highest
  padded-element-cost request goes first, priced by the same
  padded-elements accounting the padding-waste counters carry.
  Shedding expensive work first buys the most queue drain per lost
  request, which is what turns a deadline-miss burn around;
- **relax** once every watched rule has been quiet for
  ``relax_after`` consecutive cycles: the limit doubles per cycle
  back up to the configured ``max_queue``, at which point pressure is
  withdrawn entirely and admission is byte-for-byte the unregulated
  engine again.

AIMD, deliberately: multiplicative decrease reacts to a burn within
one evaluation cycle; gentle recovery avoids oscillating back into
overload (the TCP congestion-control shape, applied to a queue).

Observability: ``mxnet_serve_regulator_limit`` /
``mxnet_serve_regulator_overload`` gauges and
``mxnet_serve_regulator_adjustments_total{direction}`` per engine
(reclaimed at close), ``stats()["regulator"]``, and the rule states
themselves on ``GET /alerts``.

Enabled by ``MXNET_REGULATOR=1`` (requires telemetry + a running
history recorder, since the burn rules evaluate there).  Off by
default — the acceptance tests pin that admission behavior is then
bitwise-identical to the unregulated engine.  Tests drive
:meth:`Regulator.evaluate_once` by hand against their own
AlertManager, no thread required.
"""
from __future__ import annotations

import threading
import time

from .. import telemetry as _telemetry
from .locks import named_lock

__all__ = ["Regulator", "WATCHED_RULES"]

# the burn-rate rules the regulator actuates on (alerts.py registers
# them shared across engines): saturation = availability budget,
# deadline-miss = latency budget — both resolve by shedding load
WATCHED_RULES = ("serve_queue_saturation_burn",
                 "serve_deadline_miss_burn")


def _regulator_metric_families(reg):
    limit = reg.gauge(
        "mxnet_serve_regulator_limit",
        "effective admission-queue limit the overload regulator "
        "holds, per engine (== max_queue when relaxed / steady-state)",
        labelnames=("engine",))
    overload = reg.gauge(
        "mxnet_serve_regulator_overload",
        "1 while a watched burn-rate rule is firing and the regulator "
        "is tightening admission, else 0, per engine",
        labelnames=("engine",))
    adjustments = reg.counter(
        "mxnet_serve_regulator_adjustments_total",
        "regulator actuations by direction: tighten (limit halved "
        "under a firing burn rule) / relax (limit doubled after the "
        "burn resolved)",
        labelnames=("engine", "direction"))
    return limit, overload, adjustments


class Regulator(object):
    """One engine's overload-control loop.

    Parameters: ``admission`` (the engine's AdmissionController),
    ``engine_label`` (metric label; None = no instruments), ``name``
    (for logs/stats), ``manager``/``recorder_fn`` (injectable for
    tests; default the process alert manager and recorder),
    ``rules`` (watched rule names), ``start=False`` builds a
    regulator tests step with :meth:`evaluate_once`.
    """

    def __init__(self, admission, engine_label=None, name=None,
                 interval_s=None, floor=None, relax_after=2,
                 manager=None, recorder_fn=None, rules=WATCHED_RULES,
                 start=True):
        from .. import config
        if interval_s is None:
            interval_s = config.get("MXNET_REGULATOR_INTERVAL_MS") / 1e3
        if floor is None:
            floor = config.get("MXNET_REGULATOR_MIN_QUEUE")
        self._adm = admission
        self.name = name or "engine"
        self.engine_label = engine_label
        self.interval_s = float(interval_s)
        self.max_queue = int(admission.max_queue)
        self.floor = max(1, min(int(floor), self.max_queue))
        self.relax_after = int(relax_after)
        self.rules = tuple(rules)
        self._manager = manager
        self._recorder_fn = recorder_fn
        self._limit = self.max_queue    # effective limit (no pressure)
        self._overload = False
        self._calm_cycles = 0
        self.tightenings = 0
        self.relaxations = 0
        self.last_decision = None
        self._lock = named_lock("regulator.state")
        self._stop = threading.Event()
        self._thread = None
        self._tm = None
        if self.engine_label is not None and _telemetry.enabled():
            fams = _regulator_metric_families(_telemetry.registry())
            self._tm = tuple(
                fam.labels(engine=self.engine_label)
                if i < 2 else fam
                for i, fam in enumerate(fams))
            self._tm[0].set(self._limit)
            self._tm[1].set(0.0)
        if start:
            self._thread = threading.Thread(
                target=self._run,
                name="mxnet-serve-regulator-%s" % self.name,
                daemon=True)
            self._thread.start()

    # -------------------------------------------------------------- sensing
    def _mgr(self):
        if self._manager is not None:
            return self._manager
        return _telemetry.default_manager()

    def _recorder(self):
        if self._recorder_fn is not None:
            return self._recorder_fn()
        return _telemetry.get_recorder()

    def _rule_states(self):
        mgr = self._mgr()
        out = {}
        for name in self.rules:
            try:
                out[name] = mgr.state_of(name)
            except Exception:
                out[name] = None
        return out

    # ------------------------------------------------------------- actuation
    def evaluate_once(self, now=None):
        """One control cycle; returns the decision record (also kept
        as ``last_decision``).  Safe to call from tests without the
        thread — all state transitions happen here."""
        now = time.monotonic() if now is None else now
        states = self._rule_states()
        firing = any(s == "firing" for s in states.values())
        rec = self._recorder()
        req_rate = None
        if rec is not None:
            try:
                req_rate = rec.rate("mxnet_serve_requests_total",
                                    window_s=30.0)
            except Exception:
                req_rate = None
        action = "hold"
        with self._lock:
            if firing:
                self._overload = True
                self._calm_cycles = 0
                new = max(self.floor, self._limit // 2)
                if new < self._limit:
                    self._limit = new
                    self.tightenings += 1
                    action = "tighten"
            else:
                if self._overload:
                    self._calm_cycles += 1
                    if self._calm_cycles >= self.relax_after:
                        new = min(self.max_queue, self._limit * 2)
                        if new > self._limit:
                            self._limit = new
                            self.relaxations += 1
                            action = "relax"
                        if self._limit >= self.max_queue:
                            # steady state: withdraw pressure entirely
                            self._overload = False
                            self._calm_cycles = 0
            limit = self._limit
            pressure = limit if limit < self.max_queue else None
        # actuate OUTSIDE the regulator lock: apply_pressure delivers
        # shed futures (client callbacks run there)
        self._adm.apply_pressure(pressure)
        if self._tm is not None:
            self._tm[0].set(limit)
            self._tm[1].set(1.0 if firing else 0.0)
            if action != "hold":
                self._tm[2].labels(
                    engine=self.engine_label,
                    direction=action).inc()
        if action != "hold":
            from ..telemetry import timeline as _timeline
            _timeline.instant("regulator." + action, "regulator",
                              "regulator",
                              args={"engine": self.engine_label,
                                    "limit": limit})
            _timeline.counter("regulator.limit", "regulator",
                              "regulator", limit,
                              args={"engine": self.engine_label})
        self.last_decision = {
            "t": now, "action": action, "firing": firing,
            "rule_states": states, "limit": limit,
            "pressure": pressure, "request_rate_per_s": req_rate}
        return self.last_decision

    # ------------------------------------------------------------- lifecycle
    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:
                pass        # regulation must never die of one cycle

    def close(self):
        """Stop the loop, withdraw pressure (a closing engine must not
        keep shedding its drain), reclaim this engine's series."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self._adm.apply_pressure(None)
        except Exception:
            pass
        if self._tm is not None and _telemetry.enabled():
            _telemetry.remove_labeled_series(
                _regulator_metric_families(_telemetry.registry()),
                self.engine_label)
            self._tm = None

    def stats(self):
        with self._lock:
            return {"enabled": True,
                    "limit": self._limit,
                    "max_queue": self.max_queue,
                    "floor": self.floor,
                    "overload": self._overload,
                    "interval_s": self.interval_s,
                    "rules": list(self.rules),
                    "tightenings": self.tightenings,
                    "relaxations": self.relaxations,
                    "last_decision": self.last_decision}
