"""Shared CLI plumbing for the example suite.

Reference: example/image-classification/common/fit.py (arg groups,
kvstore creation, lr scheduling, checkpoint/resume wiring) — condensed to
the knobs that exist TPU-side.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def add_fit_args(parser):
    parser.add_argument("--network", default="resnet-50")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--kv-store", default="local",
                        help="local | device | dist_sync | dist_async")
    parser.add_argument("--optimizer", default="sgd")
    parser.add_argument("--lr-factor", type=float, default=0.1)
    parser.add_argument("--lr-step-epochs", default="",
                        help="e.g. 30,60 (epochs at which lr decays)")
    parser.add_argument("--model-prefix", default=None,
                        help="checkpoint path prefix")
    parser.add_argument("--load-epoch", type=int, default=None,
                        help="resume from this checkpoint epoch")
    parser.add_argument("--disp-batches", type=int, default=20)
    parser.add_argument("--dtype", default="float32")
    return parser


def fit(args, module, train_iter, val_iter=None, batches_per_epoch=None):
    """The common/fit.py:113 loop: kvstore, lr schedule, checkpointing."""
    import mxnet_tpu as mx
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(message)s")
    kv = args.kv_store
    lr_sched = None
    if args.lr_step_epochs and batches_per_epoch:
        steps = [int(e) * batches_per_epoch
                 for e in args.lr_step_epochs.split(",")]
        lr_sched = mx.lr_scheduler.MultiFactorScheduler(
            step=steps, factor=args.lr_factor)
    opt_params = {"learning_rate": args.lr, "wd": args.wd}
    if args.optimizer == "sgd":
        opt_params["momentum"] = args.momentum
    if lr_sched is not None:
        opt_params["lr_scheduler"] = lr_sched

    arg_params = aux_params = None
    begin = 0
    if args.load_epoch is not None and args.model_prefix:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin = args.load_epoch
    cb = []
    if args.model_prefix:
        cb.append(mx.callback.do_checkpoint(args.model_prefix))
    module.fit(train_iter, eval_data=val_iter,
               num_epoch=args.num_epochs, begin_epoch=begin,
               arg_params=arg_params, aux_params=aux_params,
               kvstore=kv, optimizer=args.optimizer,
               optimizer_params=opt_params,
               initializer=__import__("mxnet_tpu").init.Xavier(),
               batch_end_callback=mx.callback.Speedometer(
                   args.batch_size, args.disp_batches),
               epoch_end_callback=cb or None)
    return module
