"""Serving efficiency report: where do the fleet's serving FLOPs go?

The serving analog of ``step_report.py``, over the ISSUE 18 efficiency
plane (mxnet_tpu/telemetry/goodput.py).  Renders one decomposition
table per engine (and per rank, for aggregated snapshots): the four
disjoint FLOPs classes every dispatch splits into —

- **useful**: live rows x valid positions (the work a client asked for),
- **padding**: pow2-bucket batch rows and sequence-pad overhang,
- **dead-slot**: vacant decode slots riding the persistent step masked,
- **spec-rejected**: draft+verify FLOPs for speculative tokens the
  target model discarded

— which sum EXACTLY to the total (integer conservation, pinned by
tests), plus the goodput ratio, per-replica serving MFU, unpriced
dispatches, and a per-tenant accounting table (useful FLOPs, tokens,
request outcomes, mean end-to-end latency) when requests carried
``submit(tenant=...)`` labels.

Sources: a telemetry JSON snapshot (``telemetry.dump_state``, the
snapshot thread, or a rank snapshot), a live endpoint via ``--url``,
or SEVERAL rank snapshots — aggregated first (tools/telemetry_dump.py
machinery): FLOPs-class counters sum into ``rank="all"`` fleet rows,
while the MFU / goodput gauges render their min/max/argmax spread (a
summed ratio would lie; the spread names the straggling rank)::

  python tools/serve_report.py telemetry.json
  python tools/serve_report.py --url http://host:9100
  python tools/serve_report.py shared/telemetry_rank*.json
"""
import argparse
import json
import sys

from telemetry_dump import load_doc, aggregate_docs, _doc_rank

#: decomposition row order; (metric suffix, display name)
CLASSES = (("useful", "useful"),
           ("padding", "padding"),
           ("dead_slot", "dead-slot"),
           ("spec_rejected", "spec-rejected"))


def _series(metrics, name):
    return (metrics.get(name) or {}).get("series", [])


def _flops_name(cls):
    return ("mxnet_serve_flops_total" if cls == "total"
            else "mxnet_serve_flops_%s_total" % cls)


def build_report(doc):
    """{(engine, rank): table dict} from one (possibly aggregated)
    telemetry document.  ``rank`` is None for single-host snapshots;
    aggregated docs contribute their ``rank="all"`` fleet sums."""
    metrics = doc.get("metrics", {})
    out = {}
    for cls in ("total",) + tuple(c for c, _ in CLASSES):
        for s in _series(metrics, _flops_name(cls)):
            lab = s.get("labels", {})
            key = (lab.get("engine", "?"), lab.get("rank"))
            if key[1] is not None and key[1] != "all":
                continue    # per-rank detail lives in the gauge spread
            row = out.setdefault(key, {
                "engine": key[0], "rank": key[1],
                "flops": {c: 0 for c, _ in CLASSES},
                "total": 0, "replicas": {}, "tenants": {}})
            # engine totals sum over the replica label
            if cls == "total":
                row["total"] += s.get("value") or 0
                rep = lab.get("replica")
                if rep is not None:
                    row["replicas"].setdefault(rep, {})
            else:
                row["flops"][cls] += s.get("value") or 0
    for s in _series(metrics, "mxnet_serve_mfu"):
        lab = s.get("labels", {})
        key = (lab.get("engine", "?"), lab.get("rank"))
        row = out.get((key[0], None)) or out.get(key)
        if row is not None and s.get("value") is not None:
            row["replicas"].setdefault(
                lab.get("replica", "?"), {})["mfu"] = s["value"]
    for s in _series(metrics, "mxnet_serve_goodput_ratio"):
        lab = s.get("labels", {})
        row = out.get((lab.get("engine", "?"), lab.get("rank"))) \
            or out.get((lab.get("engine", "?"), None))
        if row is not None and s.get("value") is not None:
            row["goodput_gauge"] = s["value"]
    for s in _series(metrics, "mxnet_serve_unpriced_dispatches_total"):
        lab = s.get("labels", {})
        key = (lab.get("engine", "?"), lab.get("rank"))
        if key[1] is not None and key[1] != "all":
            continue
        row = out.get(key) or out.get((key[0], None))
        if row is not None:
            row["unpriced"] = (row.get("unpriced", 0)
                               + (s.get("value") or 0))
    _fold_tenants(metrics, out)
    return out


def _fold_tenants(metrics, out):
    def _row_for(lab):
        key = (lab.get("engine", "?"), lab.get("rank"))
        if key[1] is not None and key[1] != "all":
            return None
        return out.get(key) or out.get((key[0], None))

    for name, field in (("mxnet_serve_tenant_useful_flops_total",
                         "useful_flops"),
                        ("mxnet_serve_tenant_tokens_total", "tokens")):
        for s in _series(metrics, name):
            lab = s.get("labels", {})
            row = _row_for(lab)
            if row is None:
                continue
            t = row["tenants"].setdefault(lab.get("tenant", "?"),
                                          {"outcomes": {}})
            t[field] = t.get(field, 0) + (s.get("value") or 0)
    for s in _series(metrics, "mxnet_serve_tenant_requests_total"):
        lab = s.get("labels", {})
        row = _row_for(lab)
        if row is None:
            continue
        t = row["tenants"].setdefault(lab.get("tenant", "?"),
                                      {"outcomes": {}})
        oc = lab.get("outcome", "?")
        t["outcomes"][oc] = t["outcomes"].get(oc, 0) \
            + (s.get("value") or 0)
    for s in _series(metrics, "mxnet_serve_tenant_latency_ms"):
        lab = s.get("labels", {})
        row = _row_for(lab)
        if row is None or not s.get("count"):
            continue
        t = row["tenants"].setdefault(lab.get("tenant", "?"),
                                      {"outcomes": {}})
        t["latency_sum_ms"] = t.get("latency_sum_ms", 0.0) \
            + (s.get("sum") or 0.0)
        t["latency_count"] = t.get("latency_count", 0) + s["count"]


def format_table(row):
    lines = []
    head = "engine=%s" % row["engine"]
    if row.get("rank"):
        head += " rank=%s" % row["rank"]
    total = row["total"]
    lines.append("%s  (total %.6g FLOPs dispatched)" % (head, total))
    lines.append("  %-16s %16s %9s" % ("class", "FLOPs", "% total"))
    acct = 0
    for cls, disp in CLASSES:
        v = row["flops"][cls]
        acct += v
        lines.append("  %-16s %16.6g %8.2f%%"
                     % (disp, v, v / total * 1e2 if total else 0))
    lines.append("  %-16s %16.6g %8.2f%%"
                 % ("total", total, 100.0 if total else 0))
    if total and abs(acct - total) > 0.5:
        # the conservation law is pinned by tests; a broken snapshot
        # (partial scrape, mixed versions) must confess, not hide
        lines.append("  !! classes sum to %.6g != total %.6g" %
                     (acct, total))
    scal = ["goodput=%.4f" % (row["flops"]["useful"] / total)] \
        if total else []
    if row.get("goodput_gauge") is not None:
        scal.append("window_goodput=%.4f" % row["goodput_gauge"])
    if row.get("unpriced"):
        scal.append("unpriced_dispatches=%d" % row["unpriced"])
    if scal:
        lines.append("  " + "  ".join(scal))
    for rep in sorted(row["replicas"]):
        mfu = row["replicas"][rep].get("mfu")
        if mfu is not None:
            lines.append("  replica %-4s mfu=%.6f" % (rep, mfu))
    if row["tenants"]:
        lines.append("  %-16s %14s %8s %8s %12s  %s"
                     % ("tenant", "useful FLOPs", "tokens", "reqs",
                        "mean e2e ms", "outcomes"))
        for t in sorted(row["tenants"]):
            d = row["tenants"][t]
            reqs = sum(d["outcomes"].values())
            mean = (d.get("latency_sum_ms", 0.0)
                    / d["latency_count"]
                    if d.get("latency_count") else None)
            lines.append("  %-16s %14.6g %8d %8d %12s  %s"
                         % (t, d.get("useful_flops", 0),
                            d.get("tokens", 0), reqs,
                            "%.2f" % mean if mean is not None else "-",
                            ",".join("%s=%d" % kv for kv in
                                     sorted(d["outcomes"].items()))))
    return "\n".join(lines)


def format_spread(doc):
    """MFU / goodput gauge spread across ranks: aggregate_docs never
    sums gauges — the straggler (argmin MFU) is the point."""
    rows = []
    for name in ("mxnet_serve_mfu", "mxnet_serve_goodput_ratio"):
        for labels, v in sorted(((doc.get("gauge_spread") or {})
                                 .get(name) or {}).items()):
            rows.append("  %-44s min %s@rank %s, max %s@rank %s"
                        % (name + labels,
                           "%.4f" % v["min"], v["min_rank"],
                           "%.4f" % v["max"], v["max_rank"]))
    if not rows:
        return ""
    return "efficiency gauge spread across ranks (straggler view):\n" \
        + "\n".join(rows)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render the serving FLOPs-decomposition table")
    ap.add_argument("files", nargs="*",
                    help="telemetry JSON snapshot(s); two or more "
                         "rank snapshots are aggregated first")
    ap.add_argument("--url",
                    help="scrape a live MXNET_TELEMETRY_PORT endpoint "
                         "instead of reading files")
    ap.add_argument("--engine", help="only report this engine label")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report instead of text")
    args = ap.parse_args(argv)

    if args.url:
        doc = load_doc(args.url)
    elif len(args.files) == 1:
        doc = load_doc(args.files[0])
    elif len(args.files) > 1:
        used, entries = set(), []
        for i, src in enumerate(args.files):
            d = load_doc(src)
            if "text" in d:
                print("serve_report needs JSON snapshots; %r is "
                      "Prometheus text" % src, file=sys.stderr)
                return 2
            entries.append((_doc_rank(d, src, i, used), d))
        doc = aggregate_docs(entries)
    else:
        print("serve_report: pass snapshot file(s) or --url "
              "http://host:port", file=sys.stderr)
        return 2
    if "text" in doc:
        print("serve_report needs a JSON snapshot (got Prometheus "
              "text); re-dump with MXNET_TELEMETRY_SNAPSHOT_FORMAT="
              "json or use /metrics.json", file=sys.stderr)
        return 2

    report = build_report(doc)
    if args.engine:
        report = {k: v for k, v in report.items()
                  if k[0] == args.engine}
    if args.as_json:
        out = {"engines": sorted(
            report.values(),
            key=lambda r: (r["engine"], r["rank"] or "")),
            "gauge_spread": doc.get("gauge_spread") or {}}
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    if not report:
        print("(no mxnet_serve_flops_total series — did the engine "
              "run with MXNET_TELEMETRY_ON=1 and "
              "MXNET_SERVE_EFFICIENCY=1?)")
        return 1
    blocks = [format_table(report[k]) for k in sorted(
        report, key=lambda k: (k[0], k[1] or ""))]
    spread = format_spread(doc)
    if spread:
        blocks.append(spread)
    print("\n\n".join(blocks))
    return 0


if __name__ == "__main__":
    sys.exit(main())
